"""Image pipeline: decode, augmenters, ImageIter (reference:
python/mxnet/image.py, 559 LoC + the C++ src/io/ pipeline).

The reference's high-throughput path is a C++ OpenCV decode+augment chain;
here decode is cv2/PIL (gated) feeding numpy, with augmenters as pure
functions. ImageRecordIter is provided over the byte-compatible RecordIO
reader with a thread pool for decode (the C++ pipeline's replacement; wrap
in PrefetchingIter for the background-producer behavior).
"""
from __future__ import annotations

import logging
import os
import random as pyrandom
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array
from .io import DataIter, DataBatch, DataDesc
from . import recordio
from . import telemetry as _telemetry


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError:
        return None


def _imdecode_np(buf, flag=1, to_rgb=True):
    """Decode to a host numpy array (the pipeline-internal path: the hot
    decode loop must never bounce pixels through device buffers)."""
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
        if img is None:
            raise MXNetError("cannot decode image")
        if to_rgb and img.ndim == 3:
            img = img[..., ::-1]
        return np.ascontiguousarray(img)
    try:
        from PIL import Image
        import io as _io
        img = np.asarray(Image.open(_io.BytesIO(buf)).convert("RGB"))
        return img
    except ImportError:
        raise MXNetError("imdecode requires cv2 or PIL")


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode an image byte buffer -> (H, W, C) NDArray.
    reference: image.py imdecode (mx.img)."""
    return array(_imdecode_np(buf, flag, to_rgb))


def _asnp(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def _resize_short_np(src, size, interp=2):
    img = _asnp(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _resize(img, new_w, new_h, interp)


def scale_down(src_size, size):
    """Shrink a crop size to fit inside the image (reference:
    image.py:62-70, aspect preserved)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size`. reference: image.py resize_short."""
    return array(_resize_short_np(src, size, interp))


def _resize(img, w, h, interp=2):
    cv2 = _cv2()
    if cv2 is not None:
        return cv2.resize(img, (w, h), interpolation=interp)
    from PIL import Image
    return np.asarray(Image.fromarray(img.astype(np.uint8)).resize((w, h)))


def _fixed_crop_np(src, x0, y0, w, h, size=None, interp=2):
    img = _asnp(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize(out, size[0], size[1], interp)
    return out


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    return array(_fixed_crop_np(src, x0, y0, w, h, size, interp))


def _random_crop_np(src, size, interp=2):
    img = _asnp(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = pyrandom.randint(0, max(w - new_w, 0))
    y0 = pyrandom.randint(0, max(h - new_h, 0))
    out = _fixed_crop_np(img, x0, y0, min(new_w, w), min(new_h, h), size,
                         interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    out, coords = _random_crop_np(src, size, interp)
    return array(out), coords


def _center_crop_np(src, size, interp=2):
    img = _asnp(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    out = _fixed_crop_np(img, x0, y0, min(new_w, w), min(new_h, h), size,
                         interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    out, coords = _center_crop_np(src, size, interp)
    return array(out), coords


def _random_size_crop_np(src, size, min_area=0.08, ratio=(3 / 4.0, 4 / 3.0),
                         interp=2):
    img = _asnp(src)
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = pyrandom.uniform(min_area, 1.0) * area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return _fixed_crop_np(img, x0, y0, new_w, new_h, size,
                                  interp), (x0, y0, new_w, new_h)
    return _center_crop_np(src, size, interp)


def _color_normalize_np(src, mean, std=None):
    img = _asnp(src).astype(np.float32)
    img = img - _asnp(mean)
    if std is not None:
        img = img / _asnp(std)
    return img


def color_normalize(src, mean, std=None):
    return array(_color_normalize_np(src, mean, std))


# ------------------------------------------------------------- augmenters
def ResizeAug(size, interp=2):
    def aug(src):
        return [_resize_short_np(src, size, interp)]
    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [_random_crop_np(src, size, interp)[0]]
    return aug


def RandomSizedCropAug(size, min_area=0.08, ratio=(3 / 4.0, 4 / 3.0),
                       interp=2):
    def aug(src):
        return [_random_size_crop_np(src, size, min_area, ratio, interp)[0]]
    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [_center_crop_np(src, size, interp)[0]]
    return aug


def RandomOrderAug(ts):
    def aug(src):
        srcs = [src]
        ts_shuffled = list(ts)
        pyrandom.shuffle(ts_shuffled)
        for t in ts_shuffled:
            srcs = sum([t(s) for s in srcs], [])
        return srcs
    return aug


def ColorJitterAug(brightness, contrast, saturation):
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def aug(src):
        img = _asnp(src).astype(np.float32)
        if brightness > 0:
            alpha = 1.0 + pyrandom.uniform(-brightness, brightness)
            img = img * alpha
        if contrast > 0:
            alpha = 1.0 + pyrandom.uniform(-contrast, contrast)
            gray = (img * coef).sum(axis=2, keepdims=True)
            img = img * alpha + gray.mean() * (1 - alpha)
        if saturation > 0:
            alpha = 1.0 + pyrandom.uniform(-saturation, saturation)
            gray = (img * coef).sum(axis=2, keepdims=True)
            img = img * alpha + gray * (1 - alpha)
        return [img]
    return aug


def LightingAug(alphastd, eigval, eigvec):
    def aug(src):
        img = _asnp(src).astype(np.float32)
        alpha = np.random.normal(0, alphastd, size=(3,))
        rgb = np.dot(_asnp(eigvec) * alpha, _asnp(eigval))
        return [img + rgb]
    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [_color_normalize_np(src, mean, std)]
    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if pyrandom.random() < p:
            return [_asnp(src)[:, ::-1]]
        return [src]
    return aug


def CastAug():
    def aug(src):
        return [_asnp(src).astype(np.float32)]
    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """reference: image.py CreateAugmenter."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0,
                                                           4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        assert std is not None
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Pure-python image iterator over .rec or .lst/raw images.
    reference: image.py ImageIter; decode parallelized with a thread pool
    (the reference's OMP decode loop, iter_image_recordio_2.cc:28)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imgrec=None, data_name="data",
                 label_name="softmax_label", num_threads=4, **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or imgrec
        if path_imgrec:
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(path_imgidx,
                                                         path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = imgrec
            self.imgidx = None

        self.imglist = None
        if path_imglist:
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in fin:
                    line = line.strip().split("\t")
                    label = np.array(line[1:-1], dtype=np.float32)
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
                self.imgidx = imgkeys
        self.path_root = path_root

        self.shuffle = shuffle
        if num_parts > 1 and self.imgidx is not None:
            n = len(self.imgidx) // num_parts
            self.imgidx = self.imgidx[part_index * n:(part_index + 1) * n]
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.aug_list = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.data_name = data_name
        self.label_name = label_name
        self._pool = ThreadPoolExecutor(max_workers=num_threads)
        self.cur = 0
        self.seq = self.imgidx
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def _read_one(self, i=None):
        if self.seq is not None and self.imglist is None:
            s = self.imgrec.read_idx(self.seq[i])
            header, img_bytes = recordio.unpack(s)
            label = header.label
        elif self.imglist is not None:
            label, fname = self.imglist[self.seq[i]]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                img_bytes = f.read()
        else:
            s = self.imgrec.read()
            if s is None:
                return None
            header, img_bytes = recordio.unpack(s)
            label = header.label
        return label, img_bytes

    def _decode_augment(self, item):
        label, img_bytes = item
        img = _imdecode_np(img_bytes)
        for aug in self.aug_list:
            img = aug(img)[0]
        arr = _asnp(img).astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.transpose(2, 0, 1)  # HWC -> CHW (reference layout)
        return arr, label

    def next(self):
        items = []
        for _ in range(self.batch_size):
            if self.seq is not None:
                if self.cur >= len(self.seq):
                    break
                item = self._read_one(self.cur)
                self.cur += 1
            else:
                item = self._read_one()
                if item is None:
                    break
            items.append(item)
        if not items:
            raise StopIteration
        pad = self.batch_size - len(items)
        if _telemetry.enabled():
            _telemetry.counter("io.batches", iter=type(self).__name__).inc()
            _telemetry.counter("io.images_decoded").inc(len(items))
            decode_span = _telemetry.span(
                "io.decode", _hist="io.decode.seconds", images=len(items))
        else:
            decode_span = _telemetry.null_span
        with decode_span:
            decoded = list(self._pool.map(self._decode_augment, items))
        data = np.zeros((self.batch_size,) + self.data_shape,
                        dtype=np.float32)
        labels = np.zeros((self.batch_size, self.label_width),
                          dtype=np.float32)
        for i, (arr, label) in enumerate(decoded):
            data[i] = arr
            lab = np.atleast_1d(np.asarray(label, dtype=np.float32))
            labels[i, :self.label_width] = lab[:self.label_width]
        if self.label_width == 1:
            labels = labels[:, 0]
        return DataBatch([array(data)], [array(labels)], pad=pad)


# Process-wide decode-pipeline choice from the one-shot throughput
# probe: None = not probed yet, "mp" / "threads" afterwards. The probe
# runs once because the answer is a property of the host (cores, IPC
# cost), not of any one iterator.
_AUTO_PIPELINE = {"choice": None}


def _probe_img_per_sec(it, n_batches, batch_size):
    """Measured decode throughput over a few batches (img/s)."""
    import time
    n = 0
    t0 = time.perf_counter()
    try:
        for _ in range(n_batches):
            it.next()
            n += batch_size
    except StopIteration:
        pass
    dt = time.perf_counter() - t0
    return n / dt if dt > 0 else 0.0


def ImageRecordIter(path_imgrec, data_shape, batch_size, path_imgidx=None,
                    shuffle=False, rand_crop=False, rand_mirror=False,
                    mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                    resize=0, part_index=0, num_parts=1, prefetch=True,
                    data_name="data", label_name="softmax_label",
                    num_workers=None, seed=0, **kwargs):
    """Factory matching the reference's ImageRecordIter params
    (reference: iter_image_recordio_2.cc registration :559-579).

    The standard param-driven augmentation set routes to the
    multiprocess decode pipeline (mp_decode.py — the analog of the
    reference's OMP-parallel C++ parser); anything it can't express
    falls back to the in-process thread-pool ImageIter. Set
    ``num_workers=0`` (or MXNET_DECODE_WORKERS=0) to force the
    fallback.

    When neither ``num_workers`` nor ``MXNET_DECODE_WORKERS`` picks a
    pipeline, the choice is *measured*: single-core hosts go straight to
    the thread pool (the mp pipeline only adds IPC there — IO_BENCH_r05
    measured 286 img/s mp vs 379 threaded on 1 core), and multi-core
    hosts run a one-shot throughput probe of both pipelines, keeping the
    faster (``MXNET_IO_AUTOTUNE=0`` skips the probe and trusts mp)."""
    mean = None
    std = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b])
    if std_r != 1 or std_g != 1 or std_b != 1:
        std = np.array([std_r, std_g, std_b])

    env_workers = os.environ.get("MXNET_DECODE_WORKERS")
    if num_workers is None and env_workers is not None:
        num_workers = int(env_workers)
    mp_ok = (num_workers != 0
             and set(kwargs) <= {"label_width"}
             and path_imgrec is not None)

    def _threaded():
        aug_list = CreateAugmenter(data_shape, resize=resize,
                                   rand_crop=rand_crop,
                                   rand_mirror=rand_mirror,
                                   mean=mean, std=std)
        return ImageIter(batch_size, data_shape, path_imgrec=path_imgrec,
                         path_imgidx=path_imgidx, shuffle=shuffle,
                         part_index=part_index, num_parts=num_parts,
                         aug_list=aug_list, data_name=data_name,
                         label_name=label_name, **kwargs)

    def _mp():
        from .mp_decode import MPImageRecordIter
        return MPImageRecordIter(
            path_imgrec, data_shape, batch_size, path_imgidx=path_imgidx,
            label_width=kwargs.get("label_width", 1), shuffle=shuffle,
            part_index=part_index, num_parts=num_parts,
            aug_params={"resize": resize, "rand_crop": rand_crop,
                        "rand_mirror": rand_mirror,
                        "mean": None if mean is None else mean.tolist(),
                        "std": None if std is None else std.tolist()},
            num_workers=num_workers, seed=seed,
            data_name=data_name, label_name=label_name)

    # auto selection: nobody pinned a pipeline, so measure instead of
    # assuming the mp path wins (it loses on low-core hosts)
    if mp_ok and num_workers is None:
        if (os.cpu_count() or 1) <= 1:
            mp_ok = False
        elif os.environ.get("MXNET_IO_AUTOTUNE", "1") != "0":
            if _AUTO_PIPELINE["choice"] is None:
                probe_n = max(2, 128 // batch_size)
                mp_it = _mp()
                try:
                    mp_rate = _probe_img_per_sec(mp_it, probe_n, batch_size)
                finally:
                    mp_it.close()
                th_rate = _probe_img_per_sec(_threaded(), probe_n,
                                             batch_size)
                _AUTO_PIPELINE["choice"] = \
                    "mp" if mp_rate >= th_rate else "threads"
                logging.info(
                    "ImageRecordIter autotune: mp %.0f img/s vs threads "
                    "%.0f img/s -> %s", mp_rate, th_rate,
                    _AUTO_PIPELINE["choice"])
            mp_ok = _AUTO_PIPELINE["choice"] == "mp"

    from .io import PrefetchingIter
    it = _mp() if mp_ok else _threaded()
    return PrefetchingIter(it) if prefetch else it


# ---------------------------------------------------------------------------
# detection-aware augmenters + iterator (reference:
# src/io/image_det_aug_default.cc:1-667, iter_image_det_recordio.cc:578).
# Det augmenters transform (image, label) together; label is a (num_obj, 5)
# float array of rows [cls_id, x1, y1, x2, y2] with coordinates normalized
# to [0, 1] and cls_id = -1 marking padding rows.
# ---------------------------------------------------------------------------
def _det_valid(label):
    return label[:, 0] >= 0


def DetHorizontalFlipAug(p):
    """Mirror image and boxes together (reference: DefaultImageDetAugmenter
    rand_mirror_prob)."""
    def aug(src, label):
        if pyrandom.random() < p:
            img = _asnp(src)[:, ::-1]
            lab = label.copy()
            v = _det_valid(lab)
            x1 = lab[:, 1].copy()
            lab[:, 1] = np.where(v, 1.0 - lab[:, 3], lab[:, 1])
            lab[:, 3] = np.where(v, 1.0 - x1, lab[:, 3])
            return img, lab
        return src, label
    return aug


def DetRandomCropAug(min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                     area_range=(0.3, 1.0), max_attempts=25):
    """Box-aware random crop: a sampled crop is accepted only if it keeps
    at least one object center and covers >= min_object_covered of each
    kept object (reference: det_aug crop_strategies)."""
    def aug(src, label):
        img = _asnp(src)
        h, w = img.shape[:2]
        valid = _det_valid(label)
        if not valid.any():
            return src, label
        for _ in range(max_attempts):
            area = pyrandom.uniform(*area_range)
            aspect = pyrandom.uniform(*aspect_ratio_range)
            cw = min(1.0, np.sqrt(area * aspect))
            ch = min(1.0, np.sqrt(area / aspect))
            cx0 = pyrandom.uniform(0, 1.0 - cw)
            cy0 = pyrandom.uniform(0, 1.0 - ch)
            cx1, cy1 = cx0 + cw, cy0 + ch
            centers_x = (label[:, 1] + label[:, 3]) / 2
            centers_y = (label[:, 2] + label[:, 4]) / 2
            keep = valid & (centers_x > cx0) & (centers_x < cx1) & \
                (centers_y > cy0) & (centers_y < cy1)
            if not keep.any():
                continue
            # coverage of each kept box by the crop
            ix1 = np.maximum(label[:, 1], cx0)
            iy1 = np.maximum(label[:, 2], cy0)
            ix2 = np.minimum(label[:, 3], cx1)
            iy2 = np.minimum(label[:, 4], cy1)
            inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0,
                                                          None)
            box_area = (label[:, 3] - label[:, 1]) * \
                (label[:, 4] - label[:, 2])
            cov = np.where(box_area > 0, inter / np.maximum(box_area, 1e-8),
                           0.0)
            if (cov[keep] < min_object_covered).any():
                continue
            px0, py0 = int(cx0 * w), int(cy0 * h)
            px1, py1 = max(px0 + 1, int(cx1 * w)), max(py0 + 1, int(cy1 * h))
            out = img[py0:py1, px0:px1]
            lab = label.copy()
            lab[:, 0] = np.where(keep, lab[:, 0], -1.0)
            for c, (lo, span) in ((1, (cx0, cw)), (3, (cx0, cw)),
                                  (2, (cy0, ch)), (4, (cy0, ch))):
                lab[:, c] = np.clip((lab[:, c] - lo) / span, 0.0, 1.0)
            return out, lab
        return src, label
    return aug


def DetRandomPadAug(aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 2.0),
                    max_attempts=25, fill=127):
    """Place the image on a larger filled canvas, shrinking boxes
    accordingly (reference: det_aug rand_pad_prob/pad strategies)."""
    def aug(src, label):
        img = _asnp(src)
        h, w = img.shape[:2]
        for _ in range(max_attempts):
            area = pyrandom.uniform(*area_range)
            aspect = pyrandom.uniform(*aspect_ratio_range)
            nw = np.sqrt(area * aspect)
            nh = np.sqrt(area / aspect)
            if nw < 1.0 or nh < 1.0:
                continue
            ph, pw = int(round(h * nh)), int(round(w * nw))
            y0 = pyrandom.randint(0, ph - h)
            x0 = pyrandom.randint(0, pw - w)
            canvas = np.full((ph, pw) + img.shape[2:], fill,
                             dtype=img.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = img
            lab = label.copy()
            v = _det_valid(lab)
            lab[:, 1] = np.where(v, (lab[:, 1] * w + x0) / pw, lab[:, 1])
            lab[:, 3] = np.where(v, (lab[:, 3] * w + x0) / pw, lab[:, 3])
            lab[:, 2] = np.where(v, (lab[:, 2] * h + y0) / ph, lab[:, 2])
            lab[:, 4] = np.where(v, (lab[:, 4] * h + y0) / ph, lab[:, 4])
            return canvas, lab
        return src, label
    return aug


def DetResizeAug(size, interp=2):
    """Force resize to (w, h) = size — boxes are normalized, unchanged."""
    def aug(src, label):
        img = _asnp(src)
        cv2 = _cv2()
        if cv2 is not None:
            out = cv2.resize(img, size, interpolation=interp)
        else:
            ys = (np.linspace(0, img.shape[0] - 1, size[1])).astype(int)
            xs = (np.linspace(0, img.shape[1] - 1, size[0])).astype(int)
            out = img[ys][:, xs]
        return out, label
    return aug


def _det_wrap(color_aug):
    """Lift a classification (image-only) augmenter to det signature."""
    def aug(src, label):
        return color_aug(src)[0], label
    return aug


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, pca_noise=0,
                       min_object_covered=0.3,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 3.0), max_attempts=25,
                       pad_val=127, inter_method=2):
    """reference: CreateDetAugmenter (image_det_aug_default.cc params)."""
    auglist = []
    if resize > 0:
        # shorter-edge resize BEFORE crops/pads, like the reference —
        # boxes are normalized so only the pixels change
        def shorter_edge(src, label, _s=resize, _i=inter_method):
            return _resize_short_np(src, _s, _i), label
        auglist.append(shorter_edge)
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (min(area_range[0], 1.0),
                                 min(area_range[1], 1.0)), max_attempts)
        p = rand_crop

        def maybe_crop(src, label, _crop=crop, _p=p):
            if pyrandom.random() < _p:
                return _crop(src, label)
            return src, label
        auglist.append(maybe_crop)
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0),
                               max(area_range[1], 1.0)),
                              max_attempts, pad_val)
        p = rand_pad

        def maybe_pad(src, label, _pad=pad, _p=p):
            if pyrandom.random() < _p:
                return _pad(src, label)
            return src, label
        auglist.append(maybe_pad)
    auglist.append(DetResizeAug((data_shape[2], data_shape[1]),
                                inter_method))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(_det_wrap(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(_det_wrap(ColorJitterAug(brightness, contrast,
                                                saturation)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(_det_wrap(LightingAug(pca_noise, eigval, eigvec)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(_det_wrap(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(DataIter):
    """Detection iterator (reference: ImageDetRecordIter,
    iter_image_det_recordio.cc:578): yields data (N, C, H, W) and padded
    label (N, max_obj, 5). Sources: in-memory (images, labels) lists or a
    RecordIO pack via ``path_imgrec`` where each record's label is a flat
    [cls, x1, y1, x2, y2] * k vector."""

    def __init__(self, batch_size, data_shape, images=None, labels=None,
                 path_imgrec=None, shuffle=False, aug_list=None,
                 max_objects=None, data_name="data", label_name="label",
                 **kwargs):
        super().__init__(batch_size)
        self._data_shape = tuple(data_shape)
        if path_imgrec is not None:
            # hold compressed buffers, decode per batch (a full detection
            # pack decoded up front would not fit in host memory; the
            # classification ImageIter streams the same way)
            rec = recordio.MXRecordIO(path_imgrec, "r")
            images, labels = [], []
            while True:
                item = rec.read()
                if item is None:
                    break
                header, img_buf = recordio.unpack(item)
                flat = np.asarray(header.label, dtype=np.float32).reshape(
                    -1, 5)
                images.append(img_buf)
                labels.append(flat)
            rec.close()
        if images is None or labels is None:
            raise MXNetError("ImageDetIter needs images+labels or "
                             "path_imgrec")
        self._images = list(images)
        self._labels = [np.asarray(l, dtype=np.float32).reshape(-1, 5)
                        for l in labels]
        self._shuffle = shuffle
        self._aug = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape)
        self._max_obj = max_objects or max(
            (l.shape[0] for l in self._labels), default=1)
        self._order = list(range(len(self._images)))
        self._pos = 0
        self.data_name, self.label_name = data_name, label_name
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self._max_obj, 5))]

    def reset(self):
        self._pos = 0
        if self._shuffle:
            pyrandom.shuffle(self._order)

    def next(self):
        if self._pos >= len(self._order):
            raise StopIteration
        n = self.batch_size
        data = np.zeros((n,) + self._data_shape, dtype=np.float32)
        label = np.full((n, self._max_obj, 5), -1.0, dtype=np.float32)
        pad = 0
        for i in range(n):
            if self._pos >= len(self._order):
                pad += 1
                continue
            idx = self._order[self._pos]
            self._pos += 1
            img = self._images[idx]
            if isinstance(img, (bytes, bytearray)):
                img = _imdecode_np(img)
            lab = self._labels[idx].copy()
            for aug in self._aug:
                img, lab = aug(img, lab)
            img = _asnp(img).astype(np.float32)
            data[i] = img.transpose(2, 0, 1)
            k = min(lab.shape[0], self._max_obj)
            label[i, :k] = lab[:k]
        return DataBatch([array(data)], [array(label)], pad=pad)


def random_size_crop(src, size, min_area=0.08, ratio=(3 / 4.0, 4 / 3.0),
                     interp=2):
    out, coords = _random_size_crop_np(src, size, min_area, ratio, interp)
    return array(out), coords
