"""chrome://tracing JSON exporter.

Serializes the span buffer to the Trace Event Format the reference's
``MXDumpProfile`` emits (reference: src/engine/profiler.cc EmitPid/
EmitEvent — "X" complete events with ts/dur in microseconds, pid/tid
lanes, plus "M" metadata naming the lanes). The output loads in
chrome://tracing and Perfetto alongside (or instead of) the JAX xplane
trace dir the profiler also produces.
"""
from __future__ import annotations

import json
import os

from . import core
from . import stepattr as _stepattr
from . import trace as _trace

__all__ = ["trace_events", "render", "dump"]

# synthetic lane bases for the non-thread tracks: request traces get one
# lane per trace (spans of different requests overlap in time, and
# chrome nests "X" events per tid), step phases share one lane (phases
# of a step are laid out sequentially inside the step interval)
_STEP_TID = 0x5E70000
_TRACE_TID = 0x7ACE000


def trace_events(spans=None, events=None, traces=True, steps=True):
    """Build the traceEvents list: one metadata event per (pid, tid)
    lane, one "X" complete event per span, one "i" instant per event —
    plus, when present, the serve trace plane (``serve.trace/*`` lanes,
    one per request trace) and the training step-phase breakdown
    (``step.phase`` lane), so ``profiler.dump_profile()`` shows where a
    request or a train step spent its time next to the executor spans.
    """
    spans = core.get_spans() if spans is None else spans
    events = core.get_events() if events is None else events
    out = []
    pid = os.getpid()
    lanes = {}
    for s in spans:
        lanes.setdefault((s.pid, s.tid), None)
    for e in events:
        lanes.setdefault((e["pid"], e["tid"]), None)
    for i, (lpid, tid) in enumerate(sorted(lanes)):
        out.append({"name": "process_name", "ph": "M", "pid": lpid,
                    "args": {"name": "mxnet_tpu"}})
        out.append({"name": "thread_name", "ph": "M", "pid": lpid,
                    "tid": tid, "args": {"name": f"thread-{i}"}})
    for s in spans:
        args = dict(s.args)
        if s.parent is not None:
            args["parent"] = s.parent
        out.append({"name": s.name, "cat": s.name.split(".")[0],
                    "ph": "X", "ts": s.ts, "dur": s.dur,
                    "pid": s.pid, "tid": s.tid, "args": args})
    for e in events:
        out.append({"name": e["kind"], "cat": "event", "ph": "i",
                    "ts": e["ts_us"], "pid": e["pid"], "tid": e["tid"],
                    "s": "t", "args": dict(e["payload"])})

    if traces:
        by_trace = {}
        for rec in _trace.spans():
            by_trace.setdefault(rec["trace"], []).append(rec)
        for i, (tid_str, recs) in enumerate(sorted(by_trace.items())):
            lane = _TRACE_TID + i
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": lane,
                        "args": {"name": f"serve.trace/{tid_str}"}})
            for rec in recs:
                args = {k: v for k, v in rec.items()
                        if k not in ("name", "ts_us", "dur_us")}
                out.append({"name": rec["name"], "cat": "trace",
                            "ph": "X", "ts": rec["ts_us"],
                            "dur": rec["dur_us"], "pid": pid,
                            "tid": lane, "args": args})

    if steps:
        recs = _stepattr.records()
        if recs:
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": _STEP_TID,
                        "args": {"name": "step.phase"}})
        for rec in recs:
            out.append({"name": "step", "cat": "step", "ph": "X",
                        "ts": rec["ts_us"], "dur": rec["wall_us"],
                        "pid": pid, "tid": _STEP_TID,
                        "args": {"epoch": rec["epoch"],
                                 "nbatch": rec["nbatch"],
                                 "steps": rec["steps"],
                                 "straggler": rec["straggler"]}})
            # phases laid out sequentially inside the step interval in
            # their real order (wait -> assemble -> dispatch -> device)
            cursor = rec["ts_us"]
            for phase in _stepattr.PHASES:
                dur = rec["phases_us"].get(phase, 0)
                if dur <= 0:
                    continue
                out.append({"name": f"step.phase.{phase}", "cat": "step",
                            "ph": "X", "ts": cursor, "dur": dur,
                            "pid": pid, "tid": _STEP_TID, "args": {}})
                cursor += dur
    return out


def render(metadata=None, spans=None, events=None):
    """The full trace document as a dict."""
    return {"traceEvents": trace_events(spans, events),
            "displayTimeUnit": "ms",
            "otherData": dict(metadata or {})}


def dump(path, metadata=None, spans=None, events=None):
    """Write the trace JSON; returns the path."""
    doc = render(metadata, spans, events)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
