"""chrome://tracing JSON exporter.

Serializes the span buffer to the Trace Event Format the reference's
``MXDumpProfile`` emits (reference: src/engine/profiler.cc EmitPid/
EmitEvent — "X" complete events with ts/dur in microseconds, pid/tid
lanes, plus "M" metadata naming the lanes). The output loads in
chrome://tracing and Perfetto alongside (or instead of) the JAX xplane
trace dir the profiler also produces.
"""
from __future__ import annotations

import json
import os

from . import core

__all__ = ["trace_events", "render", "dump"]


def trace_events(spans=None, events=None):
    """Build the traceEvents list: one metadata event per (pid, tid)
    lane, one "X" complete event per span, one "i" instant per event."""
    spans = core.get_spans() if spans is None else spans
    events = core.get_events() if events is None else events
    out = []
    lanes = {}
    for s in spans:
        lanes.setdefault((s.pid, s.tid), None)
    for e in events:
        lanes.setdefault((e["pid"], e["tid"]), None)
    for i, (pid, tid) in enumerate(sorted(lanes)):
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": "mxnet_tpu"}})
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": f"thread-{i}"}})
    for s in spans:
        args = dict(s.args)
        if s.parent is not None:
            args["parent"] = s.parent
        out.append({"name": s.name, "cat": s.name.split(".")[0],
                    "ph": "X", "ts": s.ts, "dur": s.dur,
                    "pid": s.pid, "tid": s.tid, "args": args})
    for e in events:
        out.append({"name": e["kind"], "cat": "event", "ph": "i",
                    "ts": e["ts_us"], "pid": e["pid"], "tid": e["tid"],
                    "s": "t", "args": dict(e["payload"])})
    return out


def render(metadata=None, spans=None, events=None):
    """The full trace document as a dict."""
    return {"traceEvents": trace_events(spans, events),
            "displayTimeUnit": "ms",
            "otherData": dict(metadata or {})}


def dump(path, metadata=None, spans=None, events=None):
    """Write the trace JSON; returns the path."""
    doc = render(metadata, spans, events)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
