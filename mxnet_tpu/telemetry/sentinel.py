"""NaN/Inf sentinel: opt-in divergence tripwire with op/array attribution.

A diverging training run usually announces itself long before the loss
goes NaN — one op's output or one parameter's gradient turns non-finite
first. The sentinel catches that first occurrence and attributes it,
instead of letting it launder through hundreds more steps of arithmetic.

Two install points, both on an Executor:

* **executor-level** (the default, cheap): after every forward/backward
  completion the bound outputs (and freshly produced gradients) are
  reduced with ``isfinite().all()`` on device and pulled in ONE host
  transfer per checked window — ``interval=N`` checks every Nth step,
  bounding the sync cost. Works on the fused train step too.
* **per-op** (``per_op=True``, debug speed): reuses the Monitor's
  install point (``set_monitor_callback``), which switches the executor
  to eager per-node dispatch so every operator output is checked and
  the *op* producing the first NaN is named exactly — the observability
  analog of ``MXNET_ENGINE_TYPE=NaiveEngine`` replay debugging.

Every anomaly lands in the metrics registry
(``sentinel.anomalies{kind=...,array=...}`` counters), the flight
recorder ring (so crash reports carry the first-anomaly timeline), and
— when the span tracer is on — the event buffer. When a request trace
is active on the thread, records stamp its trace id, so diagnose links
the first NaN to its request/step tree. The policy then runs the
training-health triage ladder (health.py): ``warn`` logs and keeps
training, ``snapshot`` adds a flight-recorder report, ``checkpoint``
lands an emergency commit through the bound CheckpointManager, and
``raise`` throws :class:`AnomalyError` (which the crash guards then
dump). Default policy: MXNET_NAN_SENTINEL_POLICY, else the health
plane's MXNET_TRAIN_HEALTH_POLICY surface (rule ``sentinel``).
"""
from __future__ import annotations

import logging
import os
import re

from . import core as _core
from . import flightrec as _flightrec
from . import health as _health
from . import metrics as _metrics
from . import trace as _trace

__all__ = ["NanSentinel", "AnomalyError"]

log = logging.getLogger(__name__)


class AnomalyError(RuntimeError):
    """A sentinel with policy='raise' saw a non-finite tensor."""


def _is_float(x):
    # numpy/jax dtype kinds: f=float, c=complex, V covers bfloat16 via
    # its numpy view — jax reports bfloat16 with kind 'V' name 'bfloat16'
    kind = getattr(x.dtype, "kind", "f")
    return kind in ("f", "c") or "float" in str(x.dtype)


class NanSentinel:
    """Windowed NaN/Inf checks over executor outputs, grads, or op taps.

    Parameters
    ----------
    interval : int
        Check every Nth executor completion (window stride); per-op taps
        check every observed tensor while a window is open.
    policy : "warn" | "snapshot" | "checkpoint" | "raise"
        Triage ladder level to run on an anomaly (default:
        MXNET_NAN_SENTINEL_POLICY, else the health plane's resolution
        for rule ``sentinel`` — see telemetry/health.py).
    pattern : str
        Regex filter on array/op-output names (like Monitor's).
    check_outputs / check_grads : bool
        Which executor-level surfaces to scan.
    """

    def __init__(self, interval=1, policy=None, pattern=".*",
                 check_outputs=True, check_grads=True):
        if interval < 1:
            raise ValueError("interval must be >= 1")
        policy = policy or os.environ.get("MXNET_NAN_SENTINEL_POLICY") \
            or _health.resolve_policy("sentinel")
        if policy not in _health.LADDER:
            raise ValueError(f"policy must be one of "
                             f"{'/'.join(_health.LADDER)}, got {policy!r}")
        self.interval = int(interval)
        self.policy = policy
        self.check_outputs = check_outputs
        self.check_grads = check_grads
        self._pattern = re.compile(pattern)
        self._step = 0
        self.anomalies = []      # [{"step", "kind", "array"}], host-side

    # ------------------------------------------------------------ install
    def install(self, exe, per_op=False):
        """Attach to an Executor.

        ``per_op=True`` additionally claims the Monitor install point
        (``set_monitor_callback``) — per-op attribution at eager debug
        speed; a Monitor and a per-op sentinel can't share an executor.
        """
        exe._sentinel = self
        if per_op:
            exe.set_monitor_callback(self._observe)
        return self

    # ------------------------------------------------- per-op (tap) path
    def _observe(self, name, arr):
        """Monitor-compatible tap: check one op output immediately."""
        if not self._pattern.match(name):
            return
        data = arr.asjax()
        if not _is_float(data):
            return
        import jax.numpy as jnp
        if not bool(jnp.isfinite(data).all()):
            self._emit([("op_output", name)], self._step)

    # ------------------------------------------- executor-level hook
    def check_executor(self, exe, grads_fresh=True):
        """Scan a completed executor step (outputs + fresh grads).

        Called by Executor._finish and the fused train step. Windowed:
        only every ``interval``-th call does device math; the reduction
        stays on device and all window flags come back in one transfer.
        """
        step, self._step = self._step, self._step + 1
        if step % self.interval:
            return
        import jax
        import jax.numpy as jnp
        todo = []
        if self.check_outputs and exe._outputs:
            for nm, arr in zip(exe.output_names, exe._outputs):
                if arr is None or not self._pattern.match(nm):
                    continue
                data = arr.asjax()
                if _is_float(data):
                    todo.append(("output", nm, jnp.isfinite(data).all()))
        if self.check_grads and grads_fresh:
            for nm, g in zip(exe.arg_names, exe.grad_arrays):
                if g is None or not self._pattern.match(nm):
                    continue
                data = g.asjax()
                if _is_float(data):
                    todo.append(("gradient", nm, jnp.isfinite(data).all()))
        if not todo:
            return
        flags = jax.device_get([flag for _, _, flag in todo])
        bad = [(kind, nm) for (kind, nm, _), ok in zip(todo, flags)
               if not ok]
        if bad:
            self._emit(bad, step)

    # ---------------------------------------------------------- emission
    def _emit(self, bad, step):
        """Record anomalies everywhere, then run the triage ladder once.

        Records stamp the thread's active trace id (when one exists) so
        a served request's first NaN joins its span tree in diagnose.
        """
        tid = _trace.current_id()
        stamp = {"trace": tid} if tid else {}
        for kind, name in bad:
            self.anomalies.append({"step": step, "kind": kind,
                                   "array": name, **stamp})
            _metrics.counter("sentinel.anomalies", kind=kind,
                             array=name).inc()
            _flightrec.note("anomaly", what=kind, array=name, step=step,
                            **stamp)
            if _core.enabled():
                _core.event("anomaly", what=kind, array=name, step=step)
        desc = ", ".join(f"{kind} {name!r}" for kind, name in bad)
        msg = (f"non-finite values detected at step {step}: {desc} "
               f"(sentinel policy={self.policy})")
        # one escalation surface with the health detectors: warn logs,
        # snapshot dumps, checkpoint commits, raise throws AnomalyError
        _health.escalate("sentinel", self.policy, msg, nbatch=step)
