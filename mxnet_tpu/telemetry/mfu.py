"""MFU and roofline accounting over op cost metadata.

The registry's per-op ``flops``/``bytes_moved`` estimators (seeded in
ops/cost.py) describe ONE forward execution at concrete shapes. This
module folds them over a bound graph into:

* a **cost table** — per-op FLOPs/bytes totals for one step (with a
  backward multiplier for training), plus the coverage bookkeeping that
  keeps the numbers honest: which ops carry no metadata and how many
  compute nodes they account for;
* a **roofline** — arithmetic intensity per op against the device's
  machine balance (peak FLOP/s ÷ peak HBM bandwidth): compute-bound vs
  memory-bound, attainable fraction of peak, share of step FLOPs;
* **registry gauges** — ``mfu.model`` (the model-level MFU figure),
  ``mfu.achieved_flops_per_sec``, ``mfu.coverage``, and per-op
  ``mfu.op.flops``/``mfu.op.bytes``/``mfu.op.ai`` series that
  ``tools/diagnose.py`` renders as a roofline section.

MFU is only as honest as its denominator: peaks come from the device
kind (same table bench.py uses); off-TPU there is no peak and only
achieved-FLOP/s is reported. Coverage below ~0.9 means the figure
under-counts — run ``tools/mxlint.py --mfu-audit`` to see which ops
need metadata (analysis rule MF601 flags them per graph, too).
"""
from __future__ import annotations

from . import metrics as _metrics

__all__ = ["PEAKS", "device_peaks", "device_hbm_bytes",
           "min_vmem_budget", "cost_table", "roofline",
           "model_mfu", "record_gauges", "train_factor"]

#: device_kind -> {"bf16": peak bf16 FLOP/s, "f32": peak f32 FLOP/s,
#:                 "hbm": HBM bytes/s, "hbm_bytes": HBM capacity,
#:                 "vmem_bytes": per-core VMEM budget (the Pallas
#:                 kernel validator's tile ceiling, analysis PK901)}
PEAKS = {
    "TPU v4":      {"bf16": 275e12, "f32": 137e12, "hbm": 1228e9,
                    "hbm_bytes": 32e9, "vmem_bytes": 16 << 20},
    "TPU v5 lite": {"bf16": 197e12, "f32": 98e12,  "hbm": 819e9,
                    "hbm_bytes": 16e9, "vmem_bytes": 16 << 20},
    "TPU v5e":     {"bf16": 197e12, "f32": 98e12,  "hbm": 819e9,
                    "hbm_bytes": 16e9, "vmem_bytes": 16 << 20},
    "TPU v5p":     {"bf16": 459e12, "f32": 229e12, "hbm": 2765e9,
                    "hbm_bytes": 95e9, "vmem_bytes": 16 << 20},
    "TPU v6 lite": {"bf16": 918e12, "f32": 459e12, "hbm": 1640e9,
                    "hbm_bytes": 32e9, "vmem_bytes": 32 << 20},
    "TPU v6e":     {"bf16": 918e12, "f32": 459e12, "hbm": 1640e9,
                    "hbm_bytes": 32e9, "vmem_bytes": 32 << 20},
}

#: backward-pass FLOP multiplier per op family: weight-bearing ops run
#: ~2 extra matmul/conv-sized passes (grad_data + grad_weight); plain
#: elementwise ops roughly double; optimizer updates run once.
_TRAIN_FACTORS = {
    "Convolution": 3.0, "Deconvolution": 3.0, "FullyConnected": 3.0,
    "FusedConvBNReLU": 3.0, "RNN": 3.0, "dot": 3.0, "batch_dot": 3.0,
    "BatchNorm": 3.0,
    "attention": 3.0, "pallas_flash_attention": 3.0,
    "sgd_update": 1.0, "sgd_mom_update": 1.0, "adam_update": 1.0,
    "rmsprop_update": 1.0, "rmspropalex_update": 1.0,
    "pallas_sgd_mom_update": 1.0,
    # inference-tier ops never appear in a train graph
    "QuantizedFullyConnected": 1.0, "QuantizedConvolution": 1.0,
}
_DEFAULT_TRAIN_FACTOR = 2.0


def train_factor(op_name):
    return _TRAIN_FACTORS.get(op_name, _DEFAULT_TRAIN_FACTOR)


def device_peaks(device_kind=None, dtype="bf16"):
    """(peak_flops, peak_bytes_per_sec) for a device kind, or
    (None, None) off the table (CPU, unknown accelerators)."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None, None
    rec = PEAKS.get(device_kind)
    if rec is None:
        return None, None
    return rec.get(dtype, rec["bf16"]), rec["hbm"]


def device_hbm_bytes(device_kind=None):
    """HBM capacity of one device, or None off the table — the static
    memory planner's ME801 budget (analysis/memplan.py)."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    rec = PEAKS.get(device_kind)
    return int(rec["hbm_bytes"]) if rec else None


def min_vmem_budget():
    """The smallest per-core VMEM across known generations — the
    registration-time tile ceiling a portable Pallas kernel must fit
    (analysis rule PK901: a kernel validated here runs on every listed
    generation)."""
    return int(min(rec["vmem_bytes"] for rec in PEAKS.values()))


def cost_table(symbol, shapes, train=True):
    """Fold per-op cost metadata over one bound graph.

    ``shapes`` maps input/label names to concrete shapes (the same dict
    ``symbol.infer_shape`` takes). Returns a dict:

    ``per_op``        op -> {flops, bytes, train_flops, train_bytes,
                             nodes}
    ``flops/bytes``   forward totals; ``train_flops/train_bytes`` with
                      the backward multiplier applied
    ``uncovered``     op names with nodes in this graph but no metadata
    ``covered_nodes/compute_nodes``  node-level coverage counts
    """
    arg_shapes, _out, aux_shapes = symbol.infer_shape(**shapes)
    known = dict(zip(symbol.list_arguments(), arg_shapes))
    known.update(zip(symbol.list_auxiliary_states(), aux_shapes))
    entry_shapes = symbol._infer_entry_shapes(known)

    per_op = {}
    uncovered = {}
    covered = 0
    compute = 0
    for node in symbol._topo_nodes():
        if node.is_variable:
            continue
        compute += 1
        opdef = node.opdef()
        attrs = node.attrs
        n_aux = len(opdef.aux_names(attrs))
        in_shapes = []
        ok = True
        ins = node.inputs[:len(node.inputs) - n_aux] if n_aux \
            else node.inputs
        for inp, idx in ins:
            if inp.is_variable:
                s = known.get(inp.name)
            else:
                s = entry_shapes.get(id(inp), [None])[idx]
            if s is None or 0 in tuple(s):
                ok = False
                break
            in_shapes.append(tuple(s))
        cost = opdef.cost(attrs, in_shapes) if ok and in_shapes else None
        if cost is None:
            uncovered.setdefault(node.op, 0)
            uncovered[node.op] += 1
            continue
        covered += 1
        f = train_factor(node.op)
        rec = per_op.setdefault(node.op, {"flops": 0.0, "bytes": 0.0,
                                          "train_flops": 0.0,
                                          "train_bytes": 0.0, "nodes": 0})
        rec["flops"] += cost[0]
        rec["bytes"] += cost[1]
        rec["train_flops"] += cost[0] * f
        rec["train_bytes"] += cost[1] * f
        rec["nodes"] += 1

    key = "train_flops" if train else "flops"
    return {
        "per_op": per_op,
        "flops": sum(r["flops"] for r in per_op.values()),
        "bytes": sum(r["bytes"] for r in per_op.values()),
        "train_flops": sum(r["train_flops"] for r in per_op.values()),
        "train_bytes": sum(r["train_bytes"] for r in per_op.values()),
        "step_flops": sum(r[key] for r in per_op.values()),
        "uncovered": sorted(uncovered),
        "uncovered_nodes": int(sum(uncovered.values())),
        "covered_nodes": covered,
        "compute_nodes": compute,
    }


def roofline(table, peak_flops=None, peak_bandwidth=None, train=True,
             top=None):
    """Roofline rows per op, largest FLOPs share first.

    Each row: op, flops, bytes, share (of step FLOPs), ai (arithmetic
    intensity, FLOPs/byte), bound ('compute'|'memory'), and — when the
    peaks are known — attainable_frac (the roofline ceiling for that
    intensity, as a fraction of peak FLOP/s)."""
    fkey = "train_flops" if train else "flops"
    bkey = "train_bytes" if train else "bytes"
    total = sum(r[fkey] for r in table["per_op"].values()) or 1.0
    balance = None
    if peak_flops and peak_bandwidth:
        balance = peak_flops / peak_bandwidth       # FLOPs/byte ridge
    rows = []
    for op, rec in table["per_op"].items():
        ai = rec[fkey] / rec[bkey] if rec[bkey] else float("inf")
        row = {"op": op, "flops": rec[fkey], "bytes": rec[bkey],
               "share": rec[fkey] / total, "ai": ai, "nodes": rec["nodes"]}
        if balance is not None:
            row["bound"] = "compute" if ai >= balance else "memory"
            row["attainable_frac"] = min(1.0, ai / balance)
        else:
            # no machine balance known: classify against a generic
            # accelerator ridge of ~100 FLOPs/byte so the column stays
            # meaningful on CPU runs
            row["bound"] = "compute" if ai >= 100.0 else "memory"
        rows.append(row)
    rows.sort(key=lambda r: r["flops"], reverse=True)
    return rows[:top] if top else rows


def model_mfu(flops_per_step, step_seconds, peak_flops):
    """Model-level MFU: achieved FLOP/s over peak. None without a peak
    or a measurement."""
    if not (flops_per_step and step_seconds and peak_flops):
        return None
    return (flops_per_step / step_seconds) / peak_flops


def record_gauges(table, step_seconds=None, peak_flops=None, train=True):
    """Mirror a cost table (and optionally a measured step) into the
    metrics registry for diagnose/prometheus consumption."""
    fkey = "train_flops" if train else "flops"
    bkey = "train_bytes" if train else "bytes"
    for op, rec in table["per_op"].items():
        _metrics.gauge("mfu.op.flops", op=op).set(rec[fkey])
        _metrics.gauge("mfu.op.bytes", op=op).set(rec[bkey])
        if rec[bkey]:
            _metrics.gauge("mfu.op.ai", op=op).set(rec[fkey] / rec[bkey])
    covered = table["covered_nodes"] or 0
    compute = table["compute_nodes"] or 1
    _metrics.gauge("mfu.node_coverage").set(covered / compute)
    flops = table[fkey]
    _metrics.gauge("mfu.flops_per_step").set(flops)
    if step_seconds:
        achieved = flops / step_seconds
        _metrics.gauge("mfu.achieved_flops_per_sec").set(achieved)
        if peak_flops:
            _metrics.gauge("mfu.model").set(achieved / peak_flops)
    return table
