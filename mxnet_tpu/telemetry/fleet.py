"""Fleet-level telemetry: rank identity, versioned snapshots, merging.

Every other telemetry surface stops at the process boundary; this module
is the cross-rank layer the replica-serving and multihost bets sit on.
It answers three questions:

* **Who am I?** — ``rank()`` / ``host()`` resolve this process' fleet
  identity: an explicit ``configure(rank=...)`` override first, then the
  ``MXNET_FLEET_RANK`` env var, then the rank of a live distributed
  kvstore (registered via ``register_kvstore()`` when one is created),
  then the launcher's ``DMLC_WORKER_ID``, else 0. ``tagged()`` says
  whether any of those sources is active — single-process runs stay
  untagged so their ring records and trace spans are byte-identical to
  the pre-fleet format.
* **What happened here?** — ``snapshot()`` serializes the *full*
  metrics registry (counters, gauges, histograms with bucket bounds,
  cumulative counts and exemplars — which covers breaker ``*.state``
  gauges and ``faults.*`` counters, since those are plain registry
  series) to a versioned, JSON-pure dict stamped with rank/host/
  generation identity.
* **What happened everywhere?** — ``merge(snapshots)`` combines N
  per-rank snapshots losslessly: counters sum (exactly — they are
  integers or float adds of the same stream), gauges keep per-rank
  values plus min/max/mean, histograms merge bucket-wise so a fleet
  ``quantile(q)`` computed by ``hist_quantile()`` is within one bucket
  width of the pooled observation stream's quantile. Exemplars survive
  by re-landing on the merged bounds; on a per-bucket collision the
  highest-valued (slowest) exemplar wins.

``prometheus.render(fleet=merge(...))`` turns a merged snapshot into
one exposition text with ``rank`` labels on every sample.

Everything here is stdlib + the sibling ``metrics`` module: no jax, no
kvstore import (the kvstore registers *itself*, via a weakref, so
telemetry stays import-light and the dispatch path is untouched).
"""
from __future__ import annotations

import bisect
import os
import socket
import weakref

from . import metrics as _metrics

__all__ = ["SCHEMA_VERSION", "rank", "host", "num_workers", "generation",
           "tagged", "configure", "register_kvstore", "kvstore",
           "snapshot", "merge", "merge_histogram_records",
           "hist_quantile", "hist_exemplar"]

SCHEMA_VERSION = 1

_forced_rank = None
_forced_nworkers = None
_kv_ref = None          # weakref to the live dist kvstore, if any
_host = None


def configure(rank=None, num_workers=None):
    """Explicit identity override (tests, embedders). ``configure()``
    with no arguments clears back to env/kvstore resolution."""
    global _forced_rank, _forced_nworkers
    _forced_rank = None if rank is None else int(rank)
    _forced_nworkers = None if num_workers is None else int(num_workers)


def register_kvstore(kv):
    """Called by distributed kvstores on creation; held by weakref so a
    closed/collected store never pins or misleads."""
    global _kv_ref
    _kv_ref = weakref.ref(kv)


def _live_kvstore():
    kv = _kv_ref() if _kv_ref is not None else None
    if kv is None or getattr(kv, "_closed", False):
        return None
    return kv


def kvstore():
    """The registered live distributed kvstore, or None."""
    return _live_kvstore()


def _env_int(name):
    v = os.environ.get(name)
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def rank():
    """This process' fleet rank (see module docstring for precedence)."""
    if _forced_rank is not None:
        return _forced_rank
    r = _env_int("MXNET_FLEET_RANK")
    if r is not None:
        return r
    kv = _live_kvstore()
    if kv is not None:
        try:
            return int(kv.rank)
        except Exception:
            pass
    r = _env_int("DMLC_WORKER_ID")
    return r if r is not None else 0


def num_workers():
    """Fleet size, best effort (1 when standalone)."""
    if _forced_nworkers is not None:
        return _forced_nworkers
    kv = _live_kvstore()
    if kv is not None:
        try:
            return int(kv.num_workers)
        except Exception:
            pass
    n = _env_int("DMLC_NUM_WORKER")
    return n if n is not None else 1


def host():
    global _host
    if _host is None:
        _host = socket.gethostname()
    return _host


def generation():
    """Recovery re-exec generation (0 on a first life)."""
    g = _env_int("MXNET_RECOVERY_GENERATION")
    return g if g is not None else 0


def tagged():
    """True when this process has a real fleet identity — any rank
    source is active. Untagged (single-process) runs keep ring records
    and trace spans free of rank keys."""
    if _forced_rank is not None:
        return True
    if os.environ.get("MXNET_FLEET_RANK"):
        return True
    if _live_kvstore() is not None:
        return True
    return bool(os.environ.get("DMLC_WORKER_ID"))


# ------------------------------------------------------------- snapshot
def _series_sort_key(m):
    return (m.name, m.labels)


def snapshot():
    """The full registry + identity as a versioned, JSON-pure dict.

    Schema v1::

        {"schema": 1, "rank": int, "host": str, "pid": int,
         "num_workers": int, "generation": int,
         "counters":   [{"name", "labels": {...}, "value"}, ...],
         "gauges":     [{"name", "labels": {...}, "value"}, ...],
         "histograms": [{"name", "labels": {...},
                         "buckets": [le, ...],          # sorted bounds
                         "bucket_counts": [c, ...],      # cumulative
                         "count", "sum", "min", "max",
                         "exemplars": {"<bucket idx>": [id, value]}},
                        ...]}

    Lists are sorted by (name, labels) so two snapshots of the same
    registry state serialize identically.
    """
    counters, gauges, hists = [], [], []
    for m in sorted(_metrics.all_metrics(), key=_series_sort_key):
        labels = dict(m.labels)
        if isinstance(m, _metrics.Counter):
            counters.append({"name": m.name, "labels": labels,
                             "value": m.value})
        elif isinstance(m, _metrics.Gauge):
            gauges.append({"name": m.name, "labels": labels,
                           "value": m.value})
        elif isinstance(m, _metrics.Histogram):
            hists.append({
                "name": m.name, "labels": labels,
                "buckets": list(m.buckets),
                "bucket_counts": list(m.bucket_counts),
                "count": m.count, "sum": m.sum,
                "min": m.min, "max": m.max,
                "exemplars": {str(i): [ex[0], ex[1]]
                              for i, ex in sorted(m.exemplars.items())}})
    return {"schema": SCHEMA_VERSION, "rank": rank(), "host": host(),
            "pid": os.getpid(), "num_workers": num_workers(),
            "generation": generation(),
            "counters": counters, "gauges": gauges, "histograms": hists}


# ---------------------------------------------------------------- merge
def _series_key(name, labels):
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def merge_histogram_records(recs):
    """Bucket-wise merge of schema-v1 histogram records.

    Identical bucket bounds (the normal case — histograms of one name
    share their constructor buckets) merge exactly: cumulative counts
    sum element-wise, so every quantile of the merged record is within
    one bucket width of the pooled stream's quantile. Mismatched bounds
    merge conservatively onto the union of bounds via the cumulative
    step function (each record contributes its largest known cumulative
    count at or below the bound). Exemplars re-land on the merged
    bounds by their recorded value; per-bucket collisions keep the
    highest value (deterministic tie-break on the exemplar id).
    """
    recs = [r for r in recs if r]
    if not recs:
        return None
    bounds = recs[0]["buckets"]
    if all(r["buckets"] == bounds for r in recs[1:]):
        bounds = list(bounds)
        counts = [0] * len(bounds)
        for r in recs:
            for i, c in enumerate(r["bucket_counts"]):
                counts[i] += c
    else:
        bounds = sorted({le for r in recs for le in r["buckets"]})

        def cum_at(r, le):
            i = bisect.bisect_right(r["buckets"], le)
            return r["bucket_counts"][i - 1] if i else 0

        counts = [sum(cum_at(r, le) for r in recs) for le in bounds]
    mins = [r["min"] for r in recs if r["min"] is not None]
    maxs = [r["max"] for r in recs if r["max"] is not None]
    exemplars = {}
    for r in recs:
        for _idx, (eid, v) in sorted(r.get("exemplars", {}).items()):
            landed = bisect.bisect_left(bounds, v)
            key = str(landed)
            if key not in exemplars or (v, eid) > tuple(exemplars[key][::-1]):
                exemplars[key] = [eid, v]
    return {"buckets": bounds, "bucket_counts": counts,
            "count": sum(r["count"] for r in recs),
            "sum": sum(r["sum"] for r in recs),
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "exemplars": {k: exemplars[k] for k in sorted(exemplars)}}


def hist_quantile(rec, q):
    """``Histogram.quantile`` replayed over a (merged) histogram record
    — linear interpolation over cumulative buckets, clamped to the
    recorded max above the last bound. None while empty."""
    if not rec or not rec["count"]:
        return None
    target = q * rec["count"]
    prev_le, prev_cum = 0.0, 0
    for le, cum in zip(rec["buckets"], rec["bucket_counts"]):
        if cum >= target:
            if cum == prev_cum:
                return le
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_le + frac * (le - prev_le)
        prev_le, prev_cum = le, cum
    return rec["max"]


def hist_exemplar(rec, q):
    """The exemplar id nearest the q-quantile of a (merged) record: the
    quantile's bucket's exemplar, else the closest bucket above (at
    least as slow), else the slowest seen. None when none apply."""
    if not rec or not rec["count"] or not rec.get("exemplars"):
        return None
    exemplars = {int(k): v for k, v in rec["exemplars"].items()}
    target = q * rec["count"]
    idx = len(rec["buckets"])
    for i, cum in enumerate(rec["bucket_counts"]):
        if cum >= target:
            idx = i
            break
    for i in range(idx, len(rec["buckets"]) + 1):
        if i in exemplars:
            return exemplars[i][0]
    return exemplars[max(exemplars)][0]


def merge(snapshots):
    """N per-rank ``snapshot()`` dicts -> one fleet dict.

    * counters: exact sum plus per-rank values;
    * gauges: per-rank values plus min/max/mean across ranks;
    * histograms: a bucket-wise ``merged`` record (see
      ``merge_histogram_records``) plus the per-rank records.

    Series keys render Prometheus-style (``name{k="v"}``). Two
    snapshots claiming the same rank merge rank-wise too (counters
    sum; gauges/histogram records last-wins). Output ordering is fully
    deterministic: sorted ranks, sorted series keys.
    """
    snaps = sorted((s for s in snapshots if s),
                   key=lambda s: (int(s.get("rank", 0)), s.get("host", "")))
    for s in snaps:
        if s.get("schema") != SCHEMA_VERSION:
            raise ValueError(
                f"fleet snapshot schema {s.get('schema')!r} != "
                f"{SCHEMA_VERSION} (rank {s.get('rank')!r})")
    out = {"schema": SCHEMA_VERSION,
           "ranks": sorted({int(s.get("rank", 0)) for s in snaps}),
           "hosts": {}, "generations": {},
           "num_workers": max([int(s.get("num_workers", 1))
                               for s in snaps] or [1]),
           "counters": {}, "gauges": {}, "histograms": {}}
    for s in snaps:
        r = str(int(s.get("rank", 0)))
        out["hosts"][r] = s.get("host", "")
        out["generations"][r] = int(s.get("generation", 0))

    counters, gauges, hists = {}, {}, {}
    for s in snaps:
        r = str(int(s.get("rank", 0)))
        for rec in s.get("counters", ()):
            key = _series_key(rec["name"], rec["labels"])
            slot = counters.setdefault(
                key, {"name": rec["name"], "labels": dict(rec["labels"]),
                      "by_rank": {}})
            slot["by_rank"][r] = slot["by_rank"].get(r, 0) + rec["value"]
        for rec in s.get("gauges", ()):
            key = _series_key(rec["name"], rec["labels"])
            slot = gauges.setdefault(
                key, {"name": rec["name"], "labels": dict(rec["labels"]),
                      "by_rank": {}})
            slot["by_rank"][r] = rec["value"]
        for rec in s.get("histograms", ()):
            key = _series_key(rec["name"], rec["labels"])
            slot = hists.setdefault(
                key, {"name": rec["name"], "labels": dict(rec["labels"]),
                      "by_rank": {}})
            slot["by_rank"][r] = {k: rec[k] for k in
                                  ("buckets", "bucket_counts", "count",
                                   "sum", "min", "max", "exemplars")}

    for key in sorted(counters):
        slot = counters[key]
        slot["total"] = sum(slot["by_rank"].values())
        out["counters"][key] = slot
    for key in sorted(gauges):
        slot = gauges[key]
        vals = list(slot["by_rank"].values())
        slot["min"] = min(vals)
        slot["max"] = max(vals)
        slot["mean"] = sum(vals) / len(vals)
        out["gauges"][key] = slot
    for key in sorted(hists):
        slot = hists[key]
        slot["merged"] = merge_histogram_records(
            [slot["by_rank"][r] for r in sorted(slot["by_rank"], key=int)])
        out["histograms"][key] = slot
    return out
