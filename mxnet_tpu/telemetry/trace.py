"""Request-scoped trace plane: trace ids, span trees, bounded buffer.

The span tracer (core.py) answers "what did this *process* spend time
on"; it cannot answer "where did *this request* spend its time" — a
served request crosses the admission queue, batch coalescing, a shared
bucket dispatch and the response slice, interleaved with every other
request in flight. The trace plane adds the missing identity: a
``TraceContext`` (one ``trace_id`` + process-wide unique span ids)
rides the request from ``submit`` to its ``ResponseHandle``, and every
stage records a ``(trace, span, parent)`` triple, so the request
reconstructs to a single parented span tree after the fact — the same
shape Dapper/OpenTelemetry give a multi-service RPC, scoped to the
in-process serving stack.

Record discipline:

* spans are recorded at *finish* with explicit start/end times from the
  caller's clock — the serving scheduler passes its ``MonotonicClock``/
  ``FakeClock`` seconds, so traces are deterministic under the fake
  clock (tier-1's scripted runs assert exact trees);
* a span id may be recorded more than once (a decoder *session* root
  span grows across N token steps); consumers dedupe by ``(trace,
  span)`` keeping the last record — ``spans()``/``tree()`` do this;
* batched requests share ONE dispatch span id: the span is mirrored
  into each member request's trace under that request's root, so every
  tree is complete on its own and batch-mates are joinable on the
  shared id.

Storage is a bounded deque (``MXNET_TRACE_CAPACITY``, default 4096
records) and every record is also mirrored into the flight-recorder
ring as a ``trace.span`` record — counted under the ring's own
``MXNET_FLIGHT_RECORDER_CAPACITY`` bound like any other record, so an
always-on trace plane cannot grow memory unbounded. Sampling
(``MXNET_TRACE_SAMPLE``, fraction of requests traced, default 1.0) is
counter-based and deterministic: request k is traced iff
``floor(k*rate) > floor((k-1)*rate)`` — no rng, same decisions every
run.

Pure stdlib; any layer can import this module without ordering
constraints.
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import os
import threading

from . import fleet as _fleet
from . import flightrec as _flightrec

__all__ = ["Trace", "new_trace", "next_span_id", "record", "sample",
           "spans", "tree", "trace_ids", "roots", "clear", "configure",
           "set_current", "current", "current_id", "use"]

_DEFAULT_CAPACITY = 4096

_lock = threading.Lock()


def _env_capacity():
    try:
        return max(1, int(os.environ.get("MXNET_TRACE_CAPACITY", "")
                          or _DEFAULT_CAPACITY))
    except ValueError:
        return _DEFAULT_CAPACITY


def _env_sample():
    try:
        rate = float(os.environ.get("MXNET_TRACE_SAMPLE", "") or 1.0)
    except ValueError:
        rate = 1.0
    return min(1.0, max(0.0, rate))


_buf = collections.deque(maxlen=_env_capacity())
_sample_rate = _env_sample()
_trace_seq = itertools.count(1)
_span_seq = itertools.count(1)
_sample_count = 0


class Trace:
    """One trace identity: the ``trace_id`` plus the root span id once
    the root has been recorded (consumers parent follow-on spans —
    e.g. a decode session's per-step requests — under ``root``).
    Session traces track their start time so the growing session root
    span can be re-recorded (same span id, longer dur) per step."""

    __slots__ = ("trace_id", "root", "session", "start_s")

    def __init__(self, trace_id, session=False):
        self.trace_id = trace_id
        self.root = None
        self.session = session      # a long-lived multi-request trace
        self.start_s = None

    def __repr__(self):
        return f"Trace({self.trace_id!r}, root={self.root})"


def new_trace(session=False):
    """Allocate a fresh trace identity (cheap: one counter bump) and
    mark it the calling thread's *current* trace (latest wins), so
    out-of-band emitters — the NaN sentinel, the training-health plane —
    can stamp the active request's id without threading it through
    every call signature."""
    t = Trace(f"t{next(_trace_seq):06x}", session=session)
    set_current(t)
    return t


_tls = threading.local()


def set_current(trace):
    """Set (or clear, with None) this thread's active trace — a Trace
    or a bare trace-id string."""
    _tls.current = trace


def current():
    """This thread's active trace (Trace/str), or None."""
    return getattr(_tls, "current", None)


def current_id():
    """The active trace's id string for this thread, or None."""
    cur = getattr(_tls, "current", None)
    if cur is None:
        return None
    return cur.trace_id if isinstance(cur, Trace) else str(cur)


@contextlib.contextmanager
def use(trace):
    """Scope ``trace`` as the thread's current trace, restoring the
    previous one on exit (nested server/step scopes)."""
    prev = current()
    set_current(trace)
    try:
        yield trace
    finally:
        set_current(prev)


def next_span_id():
    """Process-wide unique span id (shared-dispatch spans allocate one
    and mirror it into several traces)."""
    return next(_span_seq)


def sample():
    """Deterministic sampling decision for the next request: True iff
    the cumulative sampled count should advance at MXNET_TRACE_SAMPLE.
    Rate 1.0 always samples; 0.0 never."""
    global _sample_count
    rate = _sample_rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    with _lock:
        k = _sample_count = _sample_count + 1
    return int(k * rate) > int((k - 1) * rate)


def record(trace, name, start_s, end_s, span_id=None, parent=None,
           **args):
    """Record one finished span into the buffer + flight ring.

    ``trace``: a Trace or a bare trace-id string. ``start_s``/``end_s``
    are caller-clock seconds (the serve scheduler clock, perf_counter,
    ...). Returns the span id used (allocating one when not given).
    """
    tid = trace.trace_id if isinstance(trace, Trace) else str(trace)
    sid = span_id if span_id is not None else next_span_id()
    rec = {"trace": tid, "span": sid,
           "parent": parent, "name": name,
           "ts_us": round(start_s * 1e6),
           "dur_us": max(0, round((end_s - start_s) * 1e6)), **args}
    if _fleet.tagged():
        rec["rank"] = _fleet.rank()
    _buf.append(rec)
    if isinstance(trace, Trace) and parent is None and trace.root is None:
        trace.root = sid
    _flightrec.note("trace.span", **rec)
    return sid


def spans(trace_id=None):
    """Recorded spans (deduped by (trace, span), last record wins),
    optionally restricted to one trace, in record order."""
    with _lock:
        raw = list(_buf)
    out = {}
    for rec in raw:
        if trace_id is not None and rec["trace"] != trace_id:
            continue
        out[(rec["trace"], rec["span"])] = rec
    return list(out.values())


def trace_ids():
    """Distinct trace ids still in the buffer, oldest first."""
    seen = []
    with _lock:
        raw = list(_buf)
    for rec in raw:
        if rec["trace"] not in seen:
            seen.append(rec["trace"])
    return seen


def roots(trace_id=None):
    """Root spans (parent is None) in the buffer, deduped."""
    return [r for r in spans(trace_id) if r["parent"] is None]


def tree(trace_id):
    """Reconstruct one trace as a nested tree.

    Returns the root node ``{.., "children": [...]}`` (children in
    start-time order), or None when the trace has no spans / no root.
    Orphan spans (parent evicted from the bounded buffer) attach under
    the root so the tree stays connected.
    """
    recs = spans(trace_id)
    if not recs:
        return None
    nodes = {r["span"]: dict(r, children=[]) for r in recs}
    root = None
    for r in recs:
        node = nodes[r["span"]]
        if r["parent"] is None and root is None:
            root = node
        elif r["parent"] in nodes and r["parent"] != r["span"]:
            nodes[r["parent"]]["children"].append(node)
    if root is None:
        return None
    for n in nodes.values():
        n["children"].sort(key=lambda c: c["ts_us"])
    # orphans: recorded parent missing (evicted) — keep them reachable
    attached = set()

    def mark(n):
        attached.add(n["span"])
        for c in n["children"]:
            mark(c)
    mark(root)
    for r in recs:
        if r["span"] not in attached and r["parent"] is not None:
            root["children"].append(nodes[r["span"]])
            mark(nodes[r["span"]])
    return root


def clear():
    """Drop buffered trace records (ids keep counting — uniqueness is
    process-lifetime) and this thread's current-trace mark."""
    _buf.clear()
    _tls.current = None


def configure(capacity=None, sample=None, reset_ids=False):
    """Adjust the trace plane (tests / long-lived servers).

    ``capacity`` resizes the bounded buffer (newest records kept),
    ``sample`` overrides MXNET_TRACE_SAMPLE, ``reset_ids`` rewinds the
    trace/span id counters (deterministic-id tests only).
    """
    global _buf, _sample_rate, _trace_seq, _span_seq, _sample_count
    if capacity is not None:
        _buf = collections.deque(_buf, maxlen=max(1, int(capacity)))
    if sample is not None:
        _sample_rate = min(1.0, max(0.0, float(sample)))
        _sample_count = 0
    if reset_ids:
        _trace_seq = itertools.count(1)
        _span_seq = itertools.count(1)
        _sample_count = 0
