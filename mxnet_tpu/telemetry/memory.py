"""Device-memory accounting: per-context live/peak bytes over NDArray
handles.

The reference tracks device memory in its storage managers
(src/storage/) — every GPU pool knows its live allocation. Here XLA owns
the real allocator, and on many backends (notably the CPU test mesh)
``device.memory_stats()`` returns nothing — so the framework keeps its
own ledger at the NDArray layer: every handle accounts its logical bytes
(``size * itemsize``) against its Context on creation, adjusts on
``_set`` swaps that change size, and releases on ``__del__``. The ledger
is therefore *handle-level*: two NDArrays aliasing one buffer count
twice, and XLA-internal scratch is invisible — but parameters,
gradients, aux state, bound inputs and outputs (the HBM that matters
for "why did this run OOM") are all NDArray-held, and the ledger's
bind/run/free deltas are deterministic, which is what
``assert_no_leak()`` needs.

Live/peak watermarks surface as registry gauges
(``memory.live_bytes{ctx=...}`` / ``memory.peak_bytes{ctx=...}``), in
``telemetry.snapshot()["memory"]``, and in flight-recorder crash
reports. Accounting is on by default (a dict lookup + integer adds per
allocation — gated with the flight recorder under 2% of a small fit
loop); MXNET_MEMORY_ACCOUNTING=0 disables it at import time.

Pure stdlib at import time; gc is touched only inside assert_no_leak.
"""
from __future__ import annotations

import contextlib
import gc
import os
import threading

from . import metrics as _metrics

__all__ = ["enabled", "on_alloc", "on_swap", "on_free", "live_bytes",
           "peak_bytes", "snapshot", "reset_peak", "assert_no_leak",
           "record_executor_bind", "batch_headroom", "program_memory"]

_enabled = os.environ.get("MXNET_MEMORY_ACCOUNTING", "1") != "0"
_lock = threading.Lock()
_stats = {}        # ctx key -> _CtxStat


class _CtxStat:
    """One context's ledger + its registry gauge views.

    The gauges are cached for hot-path updates but re-created whenever
    the metrics registry generation changes (metrics.reset() between
    runs/tests), so the registry view never goes stale while the ledger
    itself survives resets — the ledger tracks real live handles.
    """

    __slots__ = ("live", "peak", "allocs", "frees", "gen",
                 "g_live", "g_peak")

    def __init__(self, key):
        self.live = 0
        self.peak = 0
        self.allocs = 0
        self.frees = 0
        self._bind_gauges(key)

    def _bind_gauges(self, key):
        self.gen = _metrics.generation()
        self.g_live = _metrics.gauge("memory.live_bytes", ctx=key)
        self.g_peak = _metrics.gauge("memory.peak_bytes", ctx=key)
        self.g_live.value = float(self.live)
        self.g_peak.value = float(self.peak)


def enabled():
    return _enabled


def _ctx_key(ctx):
    if ctx is None:
        return "unknown"
    if isinstance(ctx, str):
        return ctx
    return f"{ctx.device_type}({ctx.device_id})"


def _stat(key):
    st = _stats.get(key)
    if st is None:
        with _lock:
            st = _stats.get(key)
            if st is None:
                st = _stats[key] = _CtxStat(key)
    elif st.gen != _metrics.generation():
        st._bind_gauges(key)
    return st


def _nbytes(data):
    return int(data.size) * data.dtype.itemsize


# ---------------------------------------------------------- NDArray hooks
def on_alloc(nd):
    """Account a freshly constructed NDArray handle.

    Stores ``(ctx_key, nbytes)`` on the handle (``nd._acct``) so swap
    and free stay O(1); handles created while accounting is disabled
    carry None and are never tracked.
    """
    if not _enabled:
        nd._acct = None
        return
    try:
        nbytes = _nbytes(nd._data)
        key = _ctx_key(nd._ctx)
    except Exception:        # tracers/odd avals: stay untracked
        nd._acct = None
        return
    nd._acct = (key, nbytes)
    st = _stat(key)
    with _lock:
        st.allocs += 1
        st.live += nbytes
        if st.live > st.peak:
            st.peak = st.live
            st.g_peak.value = float(st.peak)
        st.g_live.value = float(st.live)


def on_swap(nd):
    """Re-account after ``_set`` swapped in a new buffer.

    The overwhelmingly common swap (optimizer update, batch load) keeps
    the shape/dtype — that case exits on one integer compare.
    """
    acct = nd._acct
    if acct is None:
        return
    try:
        nbytes = _nbytes(nd._data)
    except Exception:
        return
    key, old = acct
    if nbytes == old:
        return
    nd._acct = (key, nbytes)
    st = _stat(key)
    with _lock:
        st.live += nbytes - old
        if st.live > st.peak:
            st.peak = st.live
            st.g_peak.value = float(st.peak)
        st.g_live.value = float(st.live)


def on_free(acct):
    """Release a handle's accounted bytes (called from NDArray.__del__)."""
    if acct is None:
        return
    key, nbytes = acct
    st = _stats.get(key)
    if st is None:
        return
    with _lock:
        st.frees += 1
        st.live -= nbytes
        st.g_live.value = float(st.live)


# --------------------------------------------------------------- readouts
def live_bytes(ctx=None):
    """Live accounted bytes for one context (or summed over all)."""
    if ctx is not None:
        st = _stats.get(_ctx_key(ctx))
        return st.live if st is not None else 0
    with _lock:
        return sum(st.live for st in _stats.values())


def peak_bytes(ctx=None):
    """Peak watermark for one context (or the max over all)."""
    if ctx is not None:
        st = _stats.get(_ctx_key(ctx))
        return st.peak if st is not None else 0
    with _lock:
        return max((st.peak for st in _stats.values()), default=0)


def snapshot():
    """{ctx: {live_bytes, peak_bytes, allocs, frees}} — the memory
    section of telemetry.snapshot() and of crash reports."""
    with _lock:
        return {key: {"live_bytes": st.live, "peak_bytes": st.peak,
                      "allocs": st.allocs, "frees": st.frees}
                for key, st in _stats.items()}


def reset_peak():
    """Drop peak watermarks to the current live level (run boundaries)."""
    with _lock:
        for st in _stats.values():
            st.peak = st.live
            st.g_peak.value = float(st.peak)


@contextlib.contextmanager
def assert_no_leak(ctx=None, tolerance_bytes=0):
    """Context manager asserting live bytes return to their entry level.

    Usable from tests around a bind/run/free cycle::

        with telemetry.memory.assert_no_leak():
            exe = sym.simple_bind(ctx=mx.cpu(), data=(8, 4))
            exe.forward()
            del exe

    A gc pass runs on both sides so cycles don't read as leaks; growth
    beyond ``tolerance_bytes`` in any context (or the one named by
    ``ctx``) raises AssertionError listing the offending contexts.
    """
    gc.collect()
    keys = [_ctx_key(ctx)] if ctx is not None else None
    before = {k: v["live_bytes"] for k, v in snapshot().items()}
    yield
    gc.collect()
    after = {k: v["live_bytes"] for k, v in snapshot().items()}
    leaks = []
    for k in sorted(set(before) | set(after)):
        if keys is not None and k not in keys:
            continue
        delta = after.get(k, 0) - before.get(k, 0)
        if delta > tolerance_bytes:
            leaks.append(f"{k}: +{delta} bytes live")
    if leaks:
        raise AssertionError(
            "device-memory leak across the guarded region: "
            + "; ".join(leaks))


# ------------------------------------------------------ batch headroom
def batch_headroom(budget_bytes, fixed_bytes, per_sample_bytes, buckets):
    """Largest batch bucket admissible under a device-memory budget.

    ``fixed_bytes`` is the batch-independent footprint (params,
    optimizer state, program constants); ``per_sample_bytes`` the
    batch-linear part (activations/residuals + inputs, per sample) —
    the quantity a remat policy shrinks
    (``executor_group.fused_memory_report``). Returns the largest rung
    of ``buckets`` whose estimated step peak fits the budget, or None
    when none does. This is the gate converting remat-freed HBM into
    the next-larger batch bucket (docs/performance.md).
    """
    fit = [int(b) for b in buckets
           if fixed_bytes + per_sample_bytes * int(b) <= budget_bytes]
    return max(fit) if fit else None


def program_memory(compiled):
    """Byte stats of one compiled XLA program (``jax`` Compiled object
    or anything with ``memory_analysis()``): argument/output/temp sizes.
    Best-effort — returns None where the backend exposes no analysis.
    Note: XLA:CPU's temp figure is not schedule-aware (it will not move
    under remat); the residual-set measure (``remat.residual_bytes``)
    is the backend-independent signal, this one is the on-device
    cross-check."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        return {"argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
                "alias_bytes": int(ma.alias_size_in_bytes)}
    except Exception:
        return None


# -------------------------------------------------------- executor binds
def record_executor_bind(exe):
    """Report a freshly bound executor's memory footprint.

    Arg/grad/aux bytes come from the bound NDArrays; output bytes from
    shape inference over the bound arg shapes (float32-sized estimate —
    outputs aren't allocated until the first run). Lands as
    ``executor.memory.*_bytes{ctx=...}`` gauges (last bind wins per
    context) and one flight-recorder note; returns the footprint dict.
    """
    if not _enabled:
        return None

    def total(arrays):
        n = 0
        for a in arrays:
            if a is not None:
                n += int(a.size) * a.dtype.itemsize
        return n

    fp = {"arg_bytes": total(exe.arg_arrays),
          "grad_bytes": total(exe.grad_arrays),
          "aux_bytes": total(exe.aux_arrays)}
    try:
        shapes = {nm: tuple(a.shape)
                  for nm, a in zip(exe.arg_names, exe.arg_arrays)
                  if a is not None}
        _, out_shapes, _ = exe._symbol.infer_shape(**shapes)
        out_b = 0
        for s in out_shapes:
            if s is not None:
                n = 1
                for d in s:
                    n *= int(d)
                out_b += n * 4
        fp["output_bytes"] = out_b
    except Exception:
        fp["output_bytes"] = None
    key = _ctx_key(exe._ctx)
    for name, val in fp.items():
        if val is not None:
            _metrics.gauge(f"executor.memory.{name}", ctx=key).set(val)
    from . import flightrec as _flightrec
    _flightrec.note("executor.bind", ctx=key, outputs=len(exe.output_names),
                    **{k: v for k, v in fp.items() if v is not None})
    return fp
