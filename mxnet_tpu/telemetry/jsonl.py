"""JSON-lines event-log exporter.

One self-describing JSON object per line — the machine-readable training
log that tools/parse_log.py consumes for throughput extraction (the
structured sibling of the reference's Speedometer log lines):

    {"type": "event", "kind": "batch_end", "epoch": 0, "nbatch": 3, ...}
    {"type": "span", "name": "kvstore.push", "ts_us": ..., "dur_us": ...}
    {"type": "counter", "name": "kvstore.push.bytes", "value": 123456}

Events flatten their payload into the line (epoch/nbatch/duration at top
level) so downstream line-oriented tooling (jq, parse_log) never digs
through nesting.
"""
from __future__ import annotations

import json
import os
import time

from . import core
from . import fleet as _fleet
from . import metrics as _metrics
from . import stepattr as _stepattr
from . import trace as _trace

__all__ = ["lines", "render", "dump"]


def lines(spans=True, events=True, metrics=True, traces=True, steps=True,
          meta=True):
    """Yield the log as dicts: a ``meta`` identity line first (rank /
    host / generation — tools/fleetstat.py keys per-rank dumps on it),
    then events (they are what log consumers key on), then spans in
    completion order, then the trace plane's request span-tree records,
    then step-attribution records, then the registry."""
    if meta:
        yield {"type": "meta", "schema": _fleet.SCHEMA_VERSION,
               "rank": _fleet.rank(), "host": _fleet.host(),
               "pid": os.getpid(), "num_workers": _fleet.num_workers(),
               "generation": _fleet.generation(),
               # wall clock of the dump: cross-rank staleness is only
               # comparable on wall time (ts_us is per-process
               # perf_counter time with an arbitrary epoch)
               "time_unix": time.time()}
    if events:
        for e in core.get_events():
            rec = {"type": "event", "kind": e["kind"], "ts_us": e["ts_us"]}
            for k, v in e["payload"].items():
                rec.setdefault(k, v)
            yield rec
    if spans:
        for s in core.get_spans():
            yield {"type": "span", "name": s.name, "ts_us": s.ts,
                   "dur_us": s.dur, "pid": s.pid, "tid": s.tid,
                   "parent": s.parent, "args": dict(s.args)}
    if traces:
        # one line per (trace, span): tools/diagnose.py rebuilds the
        # request span trees from exactly these records
        for rec in _trace.spans():
            yield {"type": "trace", **rec}
    if steps:
        # per-step wall + phase attribution — fleetstat's straggler
        # table reads exactly these records
        for rec in _stepattr.records():
            yield {"type": "step", **rec}
    if metrics:
        for m in _metrics.all_metrics():
            labels = dict(m.labels)
            if isinstance(m, _metrics.Counter):
                yield {"type": "counter", "name": m.name, "labels": labels,
                       "value": m.value}
            elif isinstance(m, _metrics.Gauge):
                yield {"type": "gauge", "name": m.name, "labels": labels,
                       "value": m.value}
            elif isinstance(m, _metrics.Histogram):
                yield {"type": "histogram", "name": m.name,
                       "labels": labels, "count": m.count, "sum": m.sum,
                       "min": m.min, "max": m.max, "mean": m.mean,
                       # cumulative buckets ride along so offline
                       # consumers (tools/diagnose.py serving section)
                       # can estimate p50/p99 like the live registry
                       "buckets": {str(le): c
                                   for le, c in m.cumulative()}}


def render(**kwargs):
    return "\n".join(json.dumps(rec) for rec in lines(**kwargs)) + "\n"


def dump(path, **kwargs):
    """Write the event log; returns the path."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(render(**kwargs))
    return path
