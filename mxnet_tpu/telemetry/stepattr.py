"""Training step-time attribution: per-phase breakdown + stall detector.

End-to-end img/s says a run is slow; it never says *where* — the batch
could be starved by the input pipeline (data wait), burning host time
in batch assembly, queuing dispatches, or genuinely bound on device
compute. This module splits every ``Module.fit`` step into phases:

==============  =====================================================
``data_wait``   blocking in the iterator handoff (PrefetchingIter's
                queue.get — the producer thread fell behind)
``assemble``    host-side batch staging: ``_load_batch`` /
                ``_stack_window`` + lr/wd and arg-dict preparation
``dispatch``    the jitted program call (async — returns at submit)
``device``      block-until-ready delta, measured at *window
                boundaries only* so the K-step scan fast path is not
                de-async'd (one block per K batches; K=1 blocks per
                step, which is what attribution means there)
``other``       the remainder of the step wall (metric update,
                callbacks, Python loop) — kept explicit so the phases
                always sum to the measured wall time
==============  =====================================================

Each phase lands in a ``step.phase.<name>.seconds`` histogram (per
logical batch, window phases divided by K) — the per-worker surface a
multihost aggregation pushes up — and a rolling straggler detector
flags any step whose wall time exceeds ``median + k*MAD`` over the
recent window (``MXNET_STRAGGLER_K``, default 5), recording the
offending step's phase breakdown into the flight ring (``step.
straggler``) so a stall names its phase, not just its existence.

Arming: follows the telemetry switch (``telemetry.enable()``), or force
with ``MXNET_STEP_ATTRIBUTION=1`` / off with ``=0`` independent of the
tracer. Disabled cost is one module-attr read + branch per site (under
the <2% budget benchmarks/telemetry_overhead.py gates); armed cost is
gated by the same benchmark's armed-tracing A/B lap.

The clock is injectable (``use_clock``) so deterministic tests can
script exact phase durations.
"""
from __future__ import annotations

import collections
import os
import threading
import time

from . import core as _core
from . import flightrec as _flightrec

__all__ = ["armed", "active", "clock", "use_clock", "configure",
           "step_begin", "note", "note_data_wait", "step_end",
           "records", "stragglers", "reset", "PHASES"]

PHASES = ("data_wait", "assemble", "dispatch", "device", "other")

_local = threading.local()
_lock = threading.Lock()
_records = collections.deque(maxlen=1024)   # recent finished steps
_stragglers = collections.deque(maxlen=64)
_window = collections.deque(maxlen=64)      # per-step walls, straggler base
_thresh = None          # cached straggler threshold (median + k*MAD)
_thresh_at = 0          # window appends when the cache was computed
_appends = 0
_hists = None           # cached phase-histogram handles
_hists_gen = -1
_THRESH_EVERY = 16      # recompute cadence: the rolling median moves
                        # slowly; per-step sorting would dominate the
                        # armed cost the overhead gate bounds

clock = time.perf_counter

_MIN_SAMPLES = 8        # straggler detector warm-up
_MAD_FLOOR_FRAC = 0.02  # MAD floor as a fraction of the median plus an
_MAD_FLOOR_S = 1e-4     # absolute floor: a uniform micro-step run
                        # (median ~us) must not flag scheduler noise

_env_armed = os.environ.get("MXNET_STEP_ATTRIBUTION", "")
_forced = None          # configure() override (tests/benchmarks)


def _env_k():
    try:
        return float(os.environ.get("MXNET_STRAGGLER_K", "") or 5.0)
    except ValueError:
        return 5.0


_k_mad = _env_k()


def armed():
    """Is step attribution recording? MXNET_STEP_ATTRIBUTION=1/0 wins,
    then a configure(armed=...) override, else the telemetry switch."""
    if _forced is not None:
        return _forced
    if _env_armed == "1":
        return True
    if _env_armed == "0":
        return False
    return _core._enabled


def active():
    """Is a step record open on THIS thread? (the executor's cheap
    guard: phases only record inside a fit step, so raw
    forward_backward loops never pay the boundary block)."""
    return getattr(_local, "current", None) is not None


def use_clock(fn):
    """Swap the time source (tests); returns the previous one."""
    global clock
    prev, clock = clock, fn
    return prev


_UNSET = object()


def configure(armed=_UNSET, k_mad=None):
    """Override the arming decision / straggler threshold
    (``armed=None`` restores the env/telemetry-driven default)."""
    global _forced, _k_mad, _thresh
    if armed is not _UNSET:
        _forced = armed
    if k_mad is not None:
        _k_mad = float(k_mad)
        _thresh = None


def note_data_wait(seconds):
    """Bank iterator-handoff wait measured *before* the step opens (the
    fit loop times ``next()`` first); ``step_begin`` claims it."""
    _local.pending_wait = getattr(_local, "pending_wait", 0.0) + seconds


def clear_pending_wait():
    """Drop banked data-wait (resume fast-forward skips a batch)."""
    _local.pending_wait = 0.0


def step_begin(epoch, nbatch):
    """Open a step record on this thread (no-op unless armed)."""
    if not armed():
        return
    wait = getattr(_local, "pending_wait", 0.0)
    _local.pending_wait = 0.0
    _local.current = {"epoch": epoch, "nbatch": nbatch, "t0": clock(),
                      "phases": {"data_wait": wait}}


def note(phase, seconds):
    """Add ``seconds`` to a phase of the open step (no-op without one)."""
    cur = getattr(_local, "current", None)
    if cur is None:
        return
    ph = cur["phases"]
    ph[phase] = ph.get(phase, 0.0) + seconds


def _phase_hists():
    """Cached phase-histogram handles (registry lookups cost a lock
    each; the armed-overhead gate counts every microsecond here).
    Refreshed when the metrics registry resets."""
    global _hists, _hists_gen
    from . import metrics as _metrics
    gen = _metrics.generation()
    if _hists is None or _hists_gen != gen:
        _hists = {p: _metrics.histogram(f"step.phase.{p}.seconds")
                  for p in PHASES}
        _hists["_count"] = _metrics.counter("step.count")
        _hists["_strag"] = _metrics.counter("step.stragglers")
        _hists_gen = gen
    return _hists


def _straggler_threshold():
    """median + k*MAD over the rolling window, recomputed every
    ``_THRESH_EVERY`` appends (the rolling median drifts slowly; two
    sorts per step would dominate the armed cost)."""
    global _thresh, _thresh_at
    if len(_window) < _MIN_SAMPLES:
        return None
    if _thresh is None or _appends - _thresh_at >= _THRESH_EVERY:
        win = sorted(_window)
        med = win[len(win) // 2]
        mad = sorted(abs(w - med) for w in win)[len(win) // 2]
        mad = max(mad, _MAD_FLOOR_FRAC * med, _MAD_FLOOR_S)
        _thresh = (med, med + _k_mad * mad)
        _thresh_at = _appends
    return _thresh


def step_end(steps=1):
    """Close the step: fold ``other``, feed the ``step.phase.*``
    histograms (per logical batch — window phases divide by ``steps``)
    and run the straggler detector on the per-step wall."""
    global _appends, _thresh
    cur = getattr(_local, "current", None)
    if cur is None:
        return None
    _local.current = None
    hists = _phase_hists()
    wall = (clock() - cur["t0"]) + cur["phases"].get("data_wait", 0.0)
    known = sum(cur["phases"].values())
    cur["phases"]["other"] = max(0.0, wall - known)
    steps = max(1, int(steps))
    per_step = wall / steps
    for phase in PHASES:
        hists[phase].observe(cur["phases"].get(phase, 0.0) / steps)
    hists["_count"].inc(steps)

    # the step interval opens at the iterator wait, not at step_begin —
    # [ts, ts+wall] then covers exactly the phases laid end to end
    rec = {"epoch": cur["epoch"], "nbatch": cur["nbatch"],
           "ts_us": round((cur["t0"] -
                           cur["phases"].get("data_wait", 0.0)) * 1e6),
           "wall_us": round(wall * 1e6),
           "steps": steps, "straggler": False,
           "phases_us": {p: round(cur["phases"].get(p, 0.0) * 1e6)
                         for p in PHASES}}

    thresh = _straggler_threshold()
    with _lock:
        _window.append(per_step)
        _appends += 1
    if thresh is not None and per_step > thresh[1]:
        rec["straggler"] = True
        rec["median_us"] = round(thresh[0] * 1e6)
        hists["_strag"].inc()
        with _lock:
            _stragglers.append(rec)
        _flightrec.note(
            "step.straggler", epoch=rec["epoch"],
            nbatch=rec["nbatch"], steps=steps,
            wall_us=rec["wall_us"], median_us=rec["median_us"],
            **{f"{p}_us": rec["phases_us"][p] for p in PHASES})
    with _lock:
        _records.append(rec)
    return rec


def records():
    """Recent finished step records, oldest first."""
    with _lock:
        return list(_records)


def stragglers():
    """Recent flagged stragglers, oldest first."""
    with _lock:
        return list(_stragglers)


def reset():
    """Drop step records, stragglers and the rolling window (histograms
    live in the metrics registry and reset with it)."""
    global _thresh, _thresh_at, _appends
    with _lock:
        _records.clear()
        _stragglers.clear()
        _window.clear()
        _thresh = None
        _thresh_at = _appends = 0
    _local.current = None
    _local.pending_wait = 0.0
