"""Unified telemetry: structured spans + metrics registry + exporters.

The measurement layer the reference implements engine-side in
``src/engine/profiler.cc`` (per-op exec records -> chrome://tracing via
MXDumpProfile), rebuilt framework-wide: every layer — executor
(compile/run), KVStore (push/pull/collectives), the IO pipeline, and
Module.fit — records into ONE process-wide tracer + registry, and three
exporters serialize it:

* ``telemetry.chrome_trace`` — chrome://tracing / Perfetto JSON (also
  reachable through the reference-shaped ``mx.profiler.dump_profile()``);
* ``telemetry.prometheus`` — Prometheus text exposition format;
* ``telemetry.jsonl`` — JSON-lines event log (tools/parse_log.py reads it).

Usage::

    mx.telemetry.enable()                      # off by default
    with mx.telemetry.span("my.phase", step=3):
        ...
    mx.telemetry.counter("my.items").inc(8)
    mx.telemetry.snapshot()                    # everything, as one dict
    mx.telemetry.chrome_trace.dump("trace.json")

Naming conventions: dotted lowercase ``layer.what[.unit]`` —
``executor.compile``, ``kvstore.push.bytes``, ``io.next.seconds``,
``module.fit.batch.seconds``. Histograms end in a unit; counters of
bytes end in ``.bytes``. Off by default: the disabled fast path is one
branch per site (gated <2% on a small fit loop by
benchmarks/telemetry_overhead.py).

On top of the tracer/registry sits the always-on diagnostics layer:

* ``telemetry.flightrec`` — bounded ring of recent activity + crash
  reports on exceptions escaping Executor/Module.fit/KVStore;
* ``telemetry.memory`` — per-context live/peak byte accounting over
  NDArray handles, ``assert_no_leak()`` for tests;
* ``telemetry.sentinel`` — opt-in NaN/Inf tripwire (``NanSentinel``)
  with warn-vs-raise policy and op/array attribution;
* ``tools/diagnose.py`` — renders a crash report or jsonl event log
  into a human-readable health report.
"""
from __future__ import annotations

from .core import (span, event, record_event, enable, disable, enabled,
                   clear, get_spans, get_events, null_span, wrap_dispatch)
from .metrics import (Counter, Gauge, Histogram, counter, gauge, histogram,
                      get_metric)
from .sentinel import NanSentinel, AnomalyError
from . import core
from . import metrics
from . import fleet
from . import flightrec
from . import memory
from . import mfu
from . import sentinel
from . import trace
from . import stepattr
from . import health
from . import chrome_trace
from . import prometheus
from . import jsonl
from . import opsd
from .opsd import serve_ops

__all__ = ["span", "event", "record_event", "enable", "disable", "enabled",
           "clear", "get_spans", "get_events", "null_span", "wrap_dispatch",
           "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
           "get_metric", "snapshot", "reset", "NanSentinel", "AnomalyError",
           "fleet", "flightrec", "memory", "mfu", "sentinel", "trace",
           "stepattr", "health", "chrome_trace", "prometheus", "jsonl",
           "opsd", "serve_ops"]


def snapshot():
    """The whole training step at a glance: the metrics registry plus
    span/event buffer depths and per-context memory watermarks."""
    snap = metrics.snapshot()
    snap["spans"] = len(core.get_spans())
    snap["events"] = len(core.get_events())
    snap["memory"] = memory.snapshot()
    snap["rank"] = fleet.rank()
    return snap


def reset():
    """Clear spans, events, the metrics registry, the flight-recorder
    ring, the trace-plane buffer and the step-attribution records; drop
    memory peak watermarks to current live (live accounting tracks real
    handles and is never cleared). The enabled/disabled switch is left
    as-is."""
    core.clear()
    metrics.reset()
    flightrec.clear()
    trace.clear()
    stepattr.reset()
    health.reset()
    memory.reset_peak()


# arm the live ops endpoint when the env asks for one (no-op otherwise)
opsd.maybe_serve_from_env()
