"""Flight recorder: always-on bounded activity ring + crash reports.

The span tracer (core.py) is off by default because a full trace of a
long run is unbounded; but when a pod job OOMs, stalls, or diverges at
3am, the evidence is gone unless *something* was recording. The flight
recorder is that something: a fixed-size ring (``collections.deque``
with ``maxlen``) of the most recent activity — batch boundaries,
executor dispatches, kvstore traffic, anomaly events — cheap enough to
leave on for every production run (one dict build + deque append per
record; gated <2% of a small fit loop by
benchmarks/telemetry_overhead.py).

Two feeds fill the ring:

* **always-on notes** at the framework's cardinal sites (Module.fit's
  batch loop, executor dispatch, KVStore push/pull) — these fire even
  with the span tracer disabled, so an uninstrumented run still leaves
  a timeline;
* **mirrored spans/events** whenever the tracer IS enabled (core.py
  forwards every finished span and instant event here), so an enabled
  run gets the full-resolution tail for free.

On any exception escaping ``Executor.forward/backward``, ``Module.fit``,
or KVStore push/pull, ``on_crash`` writes a crash report — ring
contents, metrics-registry snapshot, per-context memory watermarks
(telemetry.memory), jax device/backend info, filtered env — as one JSON
file in ``MXNET_CRASH_DIR`` (default: the working directory), exactly
once per exception. ``tools/diagnose.py`` renders it human-readable.

Pure stdlib at import time (jax is touched only inside dump_crash), so
any layer can import this module without ordering constraints.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import sys
import threading
import time
import traceback

from . import fleet as _fleet

__all__ = ["note", "note_event", "note_span", "enabled", "configure",
           "get_records", "clear", "on_crash", "dump_crash"]

log = logging.getLogger(__name__)

_DEFAULT_CAPACITY = 512

_enabled = os.environ.get("MXNET_FLIGHT_RECORDER", "1") != "0"
_ring = collections.deque(maxlen=max(1, int(os.environ.get(
    "MXNET_FLIGHT_RECORDER_CAPACITY", _DEFAULT_CAPACITY))))
_dump_dir = os.environ.get("MXNET_CRASH_DIR", ".")
_dump_lock = threading.Lock()
_dump_seq = 0


def enabled():
    return _enabled


def configure(capacity=None, dump_dir=None, enabled=None):
    """Adjust the recorder (ring size, crash-dump directory, on/off).

    Resizing preserves the newest entries that still fit. Defaults come
    from MXNET_FLIGHT_RECORDER / MXNET_FLIGHT_RECORDER_CAPACITY /
    MXNET_CRASH_DIR at import time.
    """
    global _ring, _dump_dir, _enabled
    if capacity is not None:
        _ring = collections.deque(_ring, maxlen=max(1, int(capacity)))
    if dump_dir is not None:
        _dump_dir = dump_dir
    if enabled is not None:
        _enabled = bool(enabled)


def note(kind, **info):
    """Append one record to the ring (no-op while disabled).

    Kept deliberately thin — one dict build, one clock read, one deque
    append — because the always-on sites sit on the training hot path.
    """
    if not _enabled:
        return
    rec = {"kind": kind, "ts_us": time.perf_counter_ns() // 1000, **info}
    if _fleet.tagged():
        rec["rank"] = _fleet.rank()
    _ring.append(rec)


def note_event(rec):
    """Mirror a core.event() record (already timestamped) into the ring."""
    if not _enabled:
        return
    out = {"kind": rec["kind"], "ts_us": rec["ts_us"], **rec["payload"]}
    if _fleet.tagged():
        out["rank"] = _fleet.rank()
    _ring.append(out)


def note_span(span):
    """Mirror a finished core.Span into the ring."""
    if not _enabled:
        return
    rec = {"kind": "span", "name": span.name, "ts_us": span.ts,
           "dur_us": span.dur, **span.args}
    if _fleet.tagged():
        rec["rank"] = _fleet.rank()
    _ring.append(rec)


def get_records():
    """The ring's contents, oldest first."""
    return list(_ring)


def clear():
    _ring.clear()


# ------------------------------------------------------------ crash dumps
def on_crash(exc, where):
    """Dump a crash report for ``exc`` exactly once; never raises.

    Nested instrumentation (an executor failure inside Module.fit) hits
    several guards with the same exception — the dump path is memoized
    on the exception object so only the innermost guard writes a file.
    Returns the report path (or None when disabled / dump failed).
    """
    if not _enabled:
        return None
    existing = getattr(exc, "_mx_crash_dump", None)
    if existing is not None:
        return existing
    try:
        path = dump_crash(exc=exc, where=where)
    except Exception:
        return None          # a broken dump must never mask the crash
    try:
        exc._mx_crash_dump = path
    except Exception:
        pass
    return path


def dump_crash(exc=None, where="", extra=None):
    """Write a crash report JSON into the configured directory.

    The report carries everything an operator needs to debug a dead run
    after the fact: the activity ring, the metrics registry, per-context
    memory watermarks, device/backend identity, and the MXNET_*/JAX_*/
    XLA_*/DMLC_* environment. Returns the written path.
    """
    global _dump_seq
    report = _build_report(exc, where, extra)
    os.makedirs(_dump_dir, exist_ok=True)
    with _dump_lock:
        _dump_seq += 1
        seq = _dump_seq
    fname = f"mxnet_crash_{os.getpid()}_{seq}.json"
    path = os.path.join(_dump_dir, fname)
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=str)
    log.error("crash report written to %s (while in %s)", path,
              where or "unknown")
    return path


def _build_report(exc, where, extra):
    report = {
        "type": "crash_report",
        "version": 1,
        "time_unix": time.time(),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "where": where,
        "pid": os.getpid(),
        "rank": _fleet.rank(),
        "host": _fleet.host(),
        "argv": list(sys.argv),
        "ring": get_records(),
    }
    if exc is not None:
        report["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc),
            "traceback": traceback.format_exception(
                type(exc), exc, exc.__traceback__),
        }
    try:
        from . import metrics as _metrics
        report["metrics"] = _metrics.snapshot()
    except Exception as e:
        report["metrics_error"] = repr(e)
    try:
        from . import memory as _memory
        report["memory"] = _memory.snapshot()
    except Exception as e:
        report["memory_error"] = repr(e)
    try:
        import jax
        report["backend"] = jax.default_backend()
        report["devices"] = [
            {"id": d.id, "platform": d.platform,
             "device_kind": d.device_kind,
             "process_index": d.process_index}
            for d in jax.local_devices()]
    except Exception as e:            # never require a live backend
        report["devices_error"] = repr(e)
    report["env"] = {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith(("MXNET_", "JAX_", "XLA_", "DMLC_", "PS_", "TPU_"))}
    if extra:
        report["extra"] = extra
    return report
