"""Span tracer core: thread-local span stack over monotonic clocks.

The reference collects per-op exec records engine-side into
``profiler.cc``'s ProfileStat ring and serializes them to chrome://tracing
JSON on MXDumpProfile. Here the analogous record is a *span*: a named,
nested interval measured with ``time.perf_counter_ns`` (monotonic,
ns-resolution) carrying the thread/process ids chrome://tracing wants.

Design constraints:

* **Off by default, near-zero when off.** ``span()`` returns a shared
  no-op context manager without allocating when telemetry is disabled, so
  instrumented hot paths (Module.fit's batch loop, KVStore.push) cost one
  function call and one branch — the tier-1 suites and production fit
  loops are unaffected (benchmarks/telemetry_overhead.py gates this).
* **Thread-safe.** The span *stack* (for parent attribution) is
  thread-local; the finished-span buffer is shared under one lock, so
  PrefetchingIter's producer thread and the main loop interleave safely.
* **Pure stdlib.** No jax/numpy imports — any layer of the framework can
  import telemetry without ordering constraints.

Spans are buffered in-process until an exporter (chrome_trace, prometheus,
jsonl) drains a copy; ``clear()`` resets between runs.
"""
from __future__ import annotations

import os
import threading
import time

from . import flightrec as _flightrec

__all__ = ["span", "event", "record_event", "enable", "disable", "enabled",
           "clear", "get_spans", "get_events", "null_span", "wrap_dispatch"]

_lock = threading.Lock()
_local = threading.local()
_spans = []        # finished Span objects, completion order
_events = []       # instant events: dicts with kind/ts_us/pid/tid/payload
_enabled = False


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


class _NullSpan:
    """Shared do-nothing span for the disabled fast path."""

    __slots__ = ()
    dur = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kwargs):
        return self


null_span = _NullSpan()


class Span:
    """One named interval. ``ts``/``dur`` are microseconds on the
    perf_counter timeline (chrome://tracing's native unit)."""

    __slots__ = ("name", "args", "ts", "dur", "pid", "tid", "parent",
                 "depth", "_hist")

    def __init__(self, name, args, hist=None):
        self.name = name
        self.args = args
        self.ts = 0
        self.dur = 0
        self.pid = os.getpid()
        self.tid = threading.get_ident()
        self.parent = None
        self.depth = 0
        self._hist = hist

    def set(self, **kwargs):
        self.args.update(kwargs)
        return self

    def __enter__(self):
        st = _stack()
        if st:
            self.parent = st[-1].name
            self.depth = len(st)
        st.append(self)
        self.ts = time.perf_counter_ns() // 1000
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur = time.perf_counter_ns() // 1000 - self.ts
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        with _lock:
            _spans.append(self)
        _flightrec.note_span(self)   # ring keeps the tail post-mortem
        if self._hist is not None:
            from .metrics import histogram
            histogram(self._hist).observe(self.dur / 1e6)
        return False


def span(name, _hist=None, **args):
    """Context manager measuring a named interval.

    No-op (shared singleton, no allocation) while telemetry is disabled.
    ``_hist`` names a histogram that additionally receives the duration
    in seconds, so one call site feeds both the trace and the registry.
    """
    if not _enabled:
        return null_span
    return Span(name, args, hist=_hist)


def event(kind, **payload):
    """Record an instant event (chrome 'i' phase / one jsonl line)."""
    if not _enabled:
        return
    rec = {"kind": kind, "ts_us": time.perf_counter_ns() // 1000,
           "pid": os.getpid(), "tid": threading.get_ident(),
           "payload": payload}
    with _lock:
        _events.append(rec)
    _flightrec.note_event(rec)


# the structured-log spelling of the same record (jsonl exporter)
record_event = event


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


def clear():
    """Drop buffered spans/events (metrics have their own reset)."""
    with _lock:
        del _spans[:]
        del _events[:]


def get_spans():
    with _lock:
        return list(_spans)


def get_events():
    with _lock:
        return list(_events)


def wrap_dispatch(fn, kind, compiled=True):
    """Wrap a (possibly jitted) program so each dispatch records a span.

    The first dispatch of a jitted program is where jax traces + XLA
    compiles, so it reports as ``executor.compile`` (the analog of the
    reference's graph-init segment in its profile) and every later one as
    ``executor.run``. Uncompiled programs (NaiveEngine) always report
    ``executor.run``. Disabled telemetry costs one extra frame + branch.

    Every call additionally bumps the untagged ``executor.dispatch``
    counter — the per-step host→device submission count that the K-step
    scan dispatch amortizes (benchmarks/step_overhead.py reads it).
    """
    state = {"first": compiled}

    def dispatch(*args):
        first, state["first"] = state["first"], False
        if not _enabled:
            if _flightrec._enabled:
                # always-on flight-recorder timing of the XLA dispatch —
                # the crash-report timeline's backbone when tracing is off
                name = "executor.compile" if first else "executor.run"
                t0 = time.perf_counter_ns()
                try:
                    return fn(*args)
                finally:
                    _flightrec.note(
                        name, program=kind,
                        dur_us=(time.perf_counter_ns() - t0) // 1000)
            return fn(*args)
        name = "executor.compile" if first else "executor.run"
        from .metrics import counter
        counter("executor.dispatch").inc()
        counter(name + ".calls", kind=kind).inc()
        with Span(name, {"kind": kind}, hist=name + ".seconds"):
            return fn(*args)

    dispatch.__wrapped__ = fn
    if hasattr(fn, "lower"):     # keep jitted introspection reachable
        dispatch.lower = fn.lower
    return dispatch
