"""Live ops endpoint: a read-only HTTP daemon over the telemetry plane.

A stdlib ``http.server`` on a daemon thread — the scrape/health surface
a fleet of workers exposes so an operator (or the fleet forensics tool)
can ask a *live* process what it knows, without signals, ptrace, or a
log round-trip. Armed by ``MXNET_OPS_PORT`` at telemetry import, or
explicitly via ``telemetry.serve_ops()``.

Routes (all GET, all read-only):

* ``/metrics`` — Prometheus text exposition of the registry; answers
  OpenMetrics (exemplars included) when the ``Accept`` header asks for
  ``application/openmetrics-text``.
* ``/healthz`` — liveness JSON: fleet identity, dead ranks from the
  live kvstore's heartbeats (``get_dead_nodes()``), circuit-breaker
  states, queue depths, last committed checkpoint seq, training-health
  state, and compiles-since-warmup. ``"ok"`` is false (HTTP 503) when
  any rank is dead, any breaker sits OPEN, or the training-health
  plane reports *diverged*.
* ``/varz`` — process vitals: filtered env, argv, mesh/device summary
  (only if jax is *already* imported — the ops thread never triggers
  the heavy import), memory-plan gauges, telemetry switch state.
* ``/tracez`` — the slowest request span trees from the trace plane.
* ``/trainz`` — the live training-health document (telemetry/health.py):
  arming, ok/degraded/diverged state, recent rule firings, and the
  rolling stat series the detectors chew on.
* ``/fleetz`` — this rank's versioned ``fleet.snapshot()`` (the lossless
  scrape ``tools/fleetstat.py --scrape`` merges across ranks).

Zero interaction with the dispatch path: handlers only *read* the
registry/ring/trace buffers (GIL-consistent snapshots of plain Python
state), never take framework locks, never touch jax. The <2% overhead
bound with a scraper hammering ``/metrics`` during a fused-step loop is
gated by benchmarks/telemetry_overhead.py.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import fleet as _fleet
from . import health as _health
from . import metrics as _metrics
from . import prometheus as _prometheus
from . import trace as _trace

__all__ = ["serve_ops", "stop_ops", "active", "maybe_serve_from_env",
           "OpsServer"]

log = logging.getLogger(__name__)

_OPENMETRICS_CT = "application/openmetrics-text; version=1.0.0; " \
                  "charset=utf-8"
_PROM_CT = "text/plain; version=0.0.4; charset=utf-8"

_BREAKER_STATES = {0: "closed", 1: "half-open", 2: "open"}

_ENV_PREFIXES = ("MXNET_", "JAX_", "XLA_", "DMLC_", "PS_", "TPU_")

_server = None
_lock = threading.Lock()


# ------------------------------------------------------------- payloads
def metrics_text(accept=""):
    """(body, content_type) for /metrics with OpenMetrics negotiation."""
    if "application/openmetrics-text" in (accept or ""):
        return _prometheus.render(openmetrics=True), _OPENMETRICS_CT
    return _prometheus.render(), _PROM_CT


def healthz():
    """The /healthz JSON document (also callable in-process)."""
    doc = {"rank": _fleet.rank(), "host": _fleet.host(),
           "pid": os.getpid(), "num_workers": _fleet.num_workers(),
           "generation": _fleet.generation()}
    kv = _fleet.kvstore()
    if kv is not None:
        kvdoc = {"attached": True}
        try:
            kvdoc["rank"] = kv.rank
            kvdoc["num_workers"] = kv.num_workers
        except Exception as e:
            kvdoc["error"] = repr(e)
        try:
            kvdoc["dead_nodes"] = sorted(kv.get_dead_nodes())
        except Exception as e:
            kvdoc["dead_nodes"] = []
            kvdoc["heartbeat_error"] = repr(e)
        doc["kvstore"] = kvdoc
    else:
        doc["kvstore"] = {"attached": False, "dead_nodes": []}
    breakers, queues, compiles = {}, {}, {}
    last_seq = None
    for m in _metrics.all_metrics():
        if not isinstance(m, _metrics.Gauge):
            continue
        if m.name.endswith(".state") and "breaker" in m.name:
            state = int(m.value)
            breakers[m.key] = {
                "state": state,
                "name": _BREAKER_STATES.get(state, str(state))}
        elif m.name.endswith("queue.depth"):
            queues[m.key] = m.value
        elif m.name == "serve.program_cache.compiles_since_warmup":
            compiles[m.key] = m.value
        elif m.name == "ckpt.last_seq":
            last_seq = m.value
    doc["breakers"] = breakers
    doc["queues"] = queues
    doc["compiles_since_warmup"] = compiles
    doc["last_ckpt_seq"] = last_seq
    health_state = _health.state()
    doc["train_health"] = {
        "state": health_state,
        "name": _health.STATE_NAMES.get(health_state, str(health_state)),
        "rules": sorted({f["rule"] for f in _health.status()["rules"]})}
    doc["ok"] = (not doc["kvstore"]["dead_nodes"] and
                 not any(b["state"] == 2 for b in breakers.values()) and
                 health_state < 2)
    return doc


def varz():
    """The /varz JSON document: env + mesh + plan summary."""
    from . import core as _core
    doc = {"pid": os.getpid(), "argv": list(sys.argv),
           "rank": _fleet.rank(), "host": _fleet.host(),
           "env": {k: v for k, v in sorted(os.environ.items())
                   if k.startswith(_ENV_PREFIXES)},
           "telemetry": {"enabled": _core.enabled(),
                         "spans": len(_core.get_spans()),
                         "events": len(_core.get_events())}}
    jax = sys.modules.get("jax")     # never *import* jax from here
    if jax is not None:
        try:
            doc["mesh"] = {
                "backend": jax.default_backend(),
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
                "local_devices": [
                    {"id": d.id, "platform": d.platform,
                     "device_kind": d.device_kind}
                    for d in jax.local_devices()]}
        except Exception as e:
            doc["mesh"] = {"error": repr(e)}
    else:
        doc["mesh"] = {"backend": None}
    plan = {}
    for m in _metrics.all_metrics():
        if isinstance(m, _metrics.Gauge) and m.name.startswith("memplan."):
            plan[m.key] = m.value
    doc["plan"] = plan
    return doc


def tracez(top=10):
    """The /tracez JSON document: slowest request trees, deepest first."""
    root_recs = sorted(_trace.roots(), key=lambda r: -r.get("dur_us", 0))
    trees = []
    for rec in root_recs[:top]:
        t = _trace.tree(rec["trace"])
        if t is not None:
            trees.append(t)
    return {"slowest": trees, "traces_buffered": len(_trace.trace_ids())}


# --------------------------------------------------------------- server
class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-opsd/1"

    def log_message(self, fmt, *args):   # keep the training log clean
        log.debug("opsd: " + fmt, *args)

    def _send(self, body, content_type, status=200):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, doc, status=200):
        self._send(json.dumps(doc, indent=2, sort_keys=True, default=str),
                   "application/json", status)

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                body, ct = metrics_text(self.headers.get("Accept", ""))
                self._send(body, ct)
            elif path == "/healthz":
                doc = healthz()
                self._send_json(doc, status=200 if doc["ok"] else 503)
            elif path == "/varz":
                self._send_json(varz())
            elif path == "/tracez":
                self._send_json(tracez())
            elif path == "/trainz":
                self._send_json(_health.status())
            elif path == "/fleetz":
                self._send_json(_fleet.snapshot())
            elif path == "/":
                self._send_json({"routes": ["/metrics", "/healthz",
                                            "/varz", "/tracez",
                                            "/trainz", "/fleetz"]})
            else:
                self._send_json({"error": f"no route {path}"}, status=404)
        except BrokenPipeError:
            pass
        except Exception as e:       # a broken handler must never kill
            try:                     # the scrape surface
                self._send_json({"error": repr(e)}, status=500)
            except Exception:
                pass


class OpsServer:
    """A running ops endpoint: ``.host``/``.port``/``.url`` + ``close()``."""

    def __init__(self, host, port):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxnet-opsd",
            daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def serve_ops(port=None, host="127.0.0.1"):
    """Start (or return the already-running) ops endpoint.

    ``port`` defaults to ``MXNET_OPS_PORT`` (0 = ephemeral — read the
    bound port back from ``.port``). The server is a daemon thread: it
    never blocks interpreter exit.
    """
    global _server
    with _lock:
        if _server is not None:
            return _server
        if port is None:
            try:
                port = int(os.environ.get("MXNET_OPS_PORT", "0") or 0)
            except ValueError:
                port = 0
        _server = OpsServer(host, int(port))
        log.info("ops endpoint listening on %s", _server.url)
        return _server


def stop_ops():
    """Shut the endpoint down (tests; production lets the daemon die
    with the process)."""
    global _server
    with _lock:
        srv, _server = _server, None
    if srv is not None:
        srv.close()


def active():
    """The running OpsServer, or None."""
    return _server


def maybe_serve_from_env():
    """Arm the endpoint iff MXNET_OPS_PORT is set (telemetry import
    calls this; a malformed value is ignored rather than fatal)."""
    port = os.environ.get("MXNET_OPS_PORT")
    if not port:
        return None
    try:
        int(port)
    except ValueError:
        log.warning("MXNET_OPS_PORT=%r is not a port; ops endpoint "
                    "not started", port)
        return None
    try:
        return serve_ops()
    except OSError as e:
        log.warning("ops endpoint failed to bind (%s); continuing "
                    "without", e)
        return None
