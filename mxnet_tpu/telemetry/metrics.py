"""Metrics registry: named counters, gauges, histograms.

The registry is the aggregate half of the telemetry subsystem (spans are
the timeline half): monotonically-increasing counters (kvstore bytes
pushed, compile-cache hits), last-value gauges (speedometer throughput),
and histograms with fixed buckets (batch/collective latencies) — the
three Prometheus core types, so the prometheus exporter is a direct
rendering.

Metrics are keyed by ``(name, sorted label items)`` like Prometheus
series; ``counter("executor.op_dispatch", op="Convolution")`` and
``op="FullyConnected"`` are distinct series under one family. Lookup is
create-or-get under a lock; mutation methods are lock-free on the GIL's
atomicity for float adds (the reference profiler tolerates the same
races in its stat counters).

Unlike spans, metric objects record regardless of the global telemetry
switch — they are plain cheap accumulators; *instrumentation sites* in
the framework guard with ``telemetry.enabled()`` so the disabled fast
path never computes label dicts or byte sizes.
"""
from __future__ import annotations

import threading

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
           "snapshot", "reset", "get_metric"]

_lock = threading.Lock()
_registry = {}     # (name, labels_tuple) -> metric object
_gen = 0           # bumped by reset() so cached metric refs can refresh

# latency-oriented default buckets (seconds), ~decade spacing with a 2/5
# split where training-step durations actually land
DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0,
                   5.0, 10.0, 60.0)


class _Metric:
    __slots__ = ("name", "labels")

    def __init__(self, name, labels):
        self.name = name
        self.labels = labels      # tuple of (k, v) pairs, sorted

    @property
    def key(self):
        """Series identity rendered Prometheus-style."""
        if not self.labels:
            return self.name
        inner = ",".join(f'{k}="{v}"' for k, v in self.labels)
        return f"{self.name}{{{inner}}}"


class Counter(_Metric):
    """Monotonic accumulator."""

    __slots__ = ("value",)

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counters only increase")
        self.value += n
        return self


class Gauge(_Metric):
    """Last-written value."""

    __slots__ = ("value",)

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.value = 0.0

    def set(self, v):
        self.value = float(v)
        return self

    def inc(self, n=1):
        self.value += n
        return self

    def dec(self, n=1):
        self.value -= n
        return self


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative bucket counts + sum/min/max.

    ``observe(v, exemplar=...)`` optionally attaches an exemplar (a
    trace id) to the bucket the observation lands in — the OpenMetrics
    exemplar concept, so a p99 latency bucket links to one concrete
    trace. Exemplars are pure side metadata: bucket counts, ``sum``,
    ``quantile`` and the default Prometheus text rendering are
    byte-identical with or without them (the golden-output test pins
    this); ``prometheus.render(openmetrics=True)`` opts into emitting
    them.
    """

    __slots__ = ("buckets", "bucket_counts", "count", "sum", "min", "max",
                 "exemplars")

    def __init__(self, name, labels, buckets=None):
        super().__init__(name, labels)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self.bucket_counts = [0] * len(self.buckets)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.exemplars = {}     # bucket index (len = +Inf) -> (id, value)

    def observe(self, v, exemplar=None):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        landed = len(self.buckets)          # +Inf overflow slot
        for i, le in enumerate(self.buckets):
            if v <= le:
                self.bucket_counts[i] += 1
                landed = min(landed, i)
        if exemplar is not None:
            self.exemplars[landed] = (str(exemplar), v)
        return self

    def exemplar(self, q):
        """The exemplar nearest the q-quantile: the one attached to the
        quantile's bucket, else the closest bucket above it (a trace
        that is at least as slow). None when no exemplar applies."""
        if not self.count or not self.exemplars:
            return None
        rank = q * self.count
        cum = 0
        idx = len(self.buckets)             # default: overflow slot
        for i, c in enumerate(self.bucket_counts):
            cum = c                         # counts are cumulative
            if c >= rank:
                idx = i
                break
        for i in range(idx, len(self.buckets) + 1):
            if i in self.exemplars:
                return self.exemplars[i][0]
        # nothing at or above: fall back to the slowest exemplar seen
        return self.exemplars[max(self.exemplars)][0]

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def cumulative(self):
        """[(le, cumulative count)] — the Prometheus _bucket series."""
        return list(zip(self.buckets, self.bucket_counts))

    def quantile(self, q):
        """Estimated q-quantile (0<=q<=1) by linear interpolation over
        the cumulative buckets — the same estimate Prometheus'
        ``histogram_quantile`` computes server-side; the serving p50/p99
        SLO readouts use it. Observations above the last bucket bound
        clamp to the recorded max. None while empty."""
        if not self.count:
            return None
        rank = q * self.count
        prev_le, prev_cum = 0.0, 0
        for le, cum in self.cumulative():
            if cum >= rank:
                if cum == prev_cum:
                    return le
                frac = (rank - prev_cum) / (cum - prev_cum)
                return prev_le + frac * (le - prev_le)
            prev_le, prev_cum = le, cum
        return self.max


def _get(cls, name, labels, **ctor):
    key = (name, tuple(sorted(labels.items())))
    with _lock:
        m = _registry.get(key)
        if m is None:
            m = cls(name, key[1], **ctor)
            _registry[key] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}")
        return m


def counter(name, **labels):
    return _get(Counter, name, labels)


def gauge(name, **labels):
    return _get(Gauge, name, labels)


def histogram(name, buckets=None, **labels):
    return _get(Histogram, name, labels, buckets=buckets)


def get_metric(name, **labels):
    """Registered metric or None (no create)."""
    return _registry.get((name, tuple(sorted(labels.items()))))


def snapshot():
    """One dict of everything: {"counters": {series: value}, "gauges":
    {series: value}, "histograms": {series: {count,sum,min,max,mean,
    buckets}}} — series keys rendered Prometheus-style."""
    with _lock:
        metrics = list(_registry.values())
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for m in metrics:
        if isinstance(m, Counter):
            out["counters"][m.key] = m.value
        elif isinstance(m, Gauge):
            out["gauges"][m.key] = m.value
        elif isinstance(m, Histogram):
            out["histograms"][m.key] = {
                "count": m.count, "sum": m.sum, "min": m.min,
                "max": m.max, "mean": m.mean,
                "buckets": {str(le): c for le, c in m.cumulative()}}
    return out


def reset():
    global _gen
    with _lock:
        _registry.clear()
        _gen += 1


def generation():
    """Registry generation counter: increments on every reset(), so
    long-lived holders of metric objects (telemetry.memory's gauge
    cache) can detect staleness with one integer compare."""
    return _gen


def all_metrics():
    with _lock:
        return list(_registry.values())
