"""Training-health plane: run statistics, divergence detection, triage.

The NaN sentinel (sentinel.py) fires once a tensor is already broken;
a production run wants the *earlier* signal — gradient global norm,
parameter norm, update/param ratio, the loss trajectory — sampled
continuously at near-zero cost, with deterministic detectors and an
automated response on top. Three layers:

* **In-program statistics** — when armed (``MXNET_TRAIN_HEALTH=1`` /
  ``fit(health=True)``) the fused/K-step scan train step
  (module/executor_group.py) computes a small fixed stat set *inside
  the already-jitted program*: per step, grad global L2 norm,
  per-loss-head loss value and a non-finite flag (extra stacked ys);
  per dispatch window, one param global L2 norm and update-ratio
  (‖Δw‖/‖w‖ over the window's delta) reading — a per-step read of the
  donated param carry would defeat XLA's in-place update. The host
  reads everything at window boundaries where it already syncs — zero
  added dispatches, and the K-step scan path stays async. The stats
  are read-only outputs: armed training is bit-identical to unarmed.
  Arming keys the program cache (``("health", True)``) so armed and
  unarmed runs never share a trace.

* **Detectors** — :class:`HealthMonitor` keeps an EMA baseline plus a
  rolling median/MAD window per series and fires deterministic rules:
  ``loss_spike`` (> median + K·MAD), ``loss_plateau`` (EMA unmoved for
  a full window), ``grad_explosion`` / ``grad_collapse``,
  ``update_ratio_high`` / ``update_ratio_low`` (out of band), and
  ``nonfinite``. Every firing lands a ``train.health.*`` metric, a
  flight-ring record carrying the full stat window, and — when a
  request trace is active on the thread — a trace-plane event.

* **Triage** — each rule resolves a policy on the ladder
  ``warn → snapshot → checkpoint → raise`` (cumulative:
  ``checkpoint`` also logs, ``raise`` also checkpoints when a manager
  is bound). ``snapshot`` writes a flight-recorder report,
  ``checkpoint`` lands an emergency ``CheckpointManager`` commit
  through the existing writer thread, ``raise`` escalates via the same
  :class:`~.sentinel.AnomalyError` path the sentinel uses. The
  NaN sentinel routes its own policy through :func:`escalate`, so both
  tripwires share one escalation surface.

Health state (ok/degraded/diverged) is a plain registry gauge
(``train.health.state``), so it rides ``fleet.snapshot()``/``merge()``
to the fleet tools unchanged; ``opsd`` ``/healthz`` flips 503 on
diverged and ``/trainz`` renders the live series.

Env surface (docs/env_var.md): ``MXNET_TRAIN_HEALTH``,
``MXNET_TRAIN_HEALTH_K``, ``MXNET_TRAIN_HEALTH_WINDOW``,
``MXNET_TRAIN_HEALTH_POLICY``.

Pure stdlib + sibling telemetry modules — no jax import, so the
detector layer is testable by feeding scripted stat dicts.
"""
from __future__ import annotations

import collections
import logging
import math
import os
import threading
import time
import weakref

from . import core as _core
from . import flightrec as _flightrec
from . import metrics as _metrics
from . import trace as _trace

__all__ = ["HealthMonitor", "LADDER", "RULES", "STATE_NAMES", "armed",
           "configure", "install", "monitor", "observe", "escalate",
           "resolve_policy", "bind_triage", "release_triage", "status",
           "state", "reset"]

log = logging.getLogger(__name__)

LADDER = ("warn", "snapshot", "checkpoint", "raise")

RULES = ("loss_spike", "loss_plateau", "grad_explosion", "grad_collapse",
         "update_ratio_high", "update_ratio_low", "nonfinite")

# rule -> health state it drives (1 degraded, 2 diverged)
_SEVERITY = {"loss_spike": 2, "grad_explosion": 2, "nonfinite": 2,
             "loss_plateau": 1, "grad_collapse": 1,
             "update_ratio_high": 1, "update_ratio_low": 1,
             "sentinel": 2}

STATE_NAMES = {0: "ok", 1: "degraded", 2: "diverged"}

_MIN_SAMPLES = 8        # MAD detectors' warm-up (stepattr discipline)
_THRESH_EVERY = 16      # threshold recompute cadence over the window
_MAD_FLOOR_FRAC = 0.02  # MAD floor as a fraction of |median|, plus an
_MAD_FLOOR_ABS = 1e-8   # absolute floor — a flat series must not flag
                        # float noise

_env_armed = os.environ.get("MXNET_TRAIN_HEALTH", "")
_forced = None          # configure(armed=...) / fit(health=...) override
_UNSET = object()

_lock = threading.Lock()
_monitor = None         # process-wide HealthMonitor (lazy)
_triage = None          # weakref to the module fit() is driving


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def armed():
    """Is the health plane recording? A ``configure(armed=...)`` /
    ``fit(health=...)`` override wins, then ``MXNET_TRAIN_HEALTH=1/0``;
    default off (the stats change the fused program's cache key, so
    arming is always an explicit decision, never implied by the span
    tracer switch)."""
    if _forced is not None:
        return _forced
    return _env_armed == "1"


def configure(armed=_UNSET, **kwargs):
    """Override arming (``armed=None`` restores the env default) and/or
    rebuild the process monitor with new detector knobs (any
    :class:`HealthMonitor` constructor kwarg)."""
    global _forced, _monitor
    if armed is not _UNSET:
        _forced = armed
    if kwargs:
        with _lock:
            _monitor = HealthMonitor(**kwargs)


def install(mon):
    """Install a caller-built :class:`HealthMonitor` as the process
    monitor (``fit(health=HealthMonitor(...))``) and arm the plane."""
    global _monitor, _forced
    with _lock:
        _monitor = mon
    _forced = True
    return mon


def monitor():
    """The process-wide monitor, created on first use."""
    global _monitor
    with _lock:
        if _monitor is None:
            _monitor = HealthMonitor()
        return _monitor


def observe(stats, epoch=0, nbatch=0):
    """Feed one step's stat dict into the process monitor; returns the
    list of rule firings (each carrying the resolved policy)."""
    return monitor().observe(stats, epoch=epoch, nbatch=nbatch)


def state():
    """Current health state: 0 ok / 1 degraded / 2 diverged."""
    mon = _monitor
    return 0 if mon is None else mon.state


def status():
    """The live health document (/trainz): arming, state, recent rule
    firings, and the rolling series tails. Cheap; never creates the
    monitor."""
    mon = _monitor
    doc = {"armed": armed(), "state": 0, "state_name": STATE_NAMES[0],
           "observations": 0, "rules": [], "series": {}}
    if mon is None:
        return doc
    doc["state"] = mon.state
    doc["state_name"] = STATE_NAMES.get(mon.state, str(mon.state))
    doc["observations"] = mon.observations
    doc["rules"] = mon.firings()
    doc["series"] = mon.series()
    return doc


# ------------------------------------------------------------- policies
def _parse_policy_spec(spec):
    """``MXNET_TRAIN_HEALTH_POLICY`` grammar: a bare ladder name sets
    the default for every rule; ``rule=policy`` tokens (comma-separated)
    override per rule — e.g. ``"warn"`` or
    ``"checkpoint,nonfinite=raise,sentinel=raise"``."""
    default = "warn"
    per_rule = {}
    for tok in str(spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" in tok:
            rule, _, pol = tok.partition("=")
            rule, pol = rule.strip(), pol.strip()
        else:
            rule, pol = None, tok
        if pol not in LADDER:
            log.warning("MXNET_TRAIN_HEALTH_POLICY: unknown policy %r "
                        "(want one of %s); ignored", pol, "/".join(LADDER))
            continue
        if rule is None:
            default = pol
        else:
            per_rule[rule] = pol
    return default, per_rule


def resolve_policy(rule, override=None):
    """The ladder policy for ``rule``: an explicit override first, then
    the ``MXNET_TRAIN_HEALTH_POLICY`` spec (per-rule token, else its
    default), else ``warn``. The sentinel resolves its policy here too
    (rule ``"sentinel"``), unifying both tripwires' env surface."""
    if override is not None:
        return override
    default, per_rule = _parse_policy_spec(
        os.environ.get("MXNET_TRAIN_HEALTH_POLICY", ""))
    return per_rule.get(rule, default)


def bind_triage(module):
    """Register the module a fit loop is driving so ``checkpoint``-level
    escalations (from the detector OR the sentinel) can land an
    emergency commit through its CheckpointManager. Held by weakref."""
    global _triage
    _triage = weakref.ref(module)


def release_triage():
    global _triage
    _triage = None


def _triage_module():
    ref = _triage
    return ref() if ref is not None else None


def escalate(rule, policy, message, module=None, epoch=0, nbatch=0):
    """Run the triage ladder for one firing. Cumulative: every level
    logs; ``snapshot`` additionally writes a flight-recorder report;
    ``checkpoint`` lands an emergency commit through the bound module's
    CheckpointManager writer thread; ``raise`` throws
    :class:`~.sentinel.AnomalyError` (after the emergency commit, so
    the raise path is resumable)."""
    from .. import faults as _faults
    level = LADDER.index(policy) if policy in LADDER else 0
    log.warning("train health: rule %r fired (policy=%s): %s",
                rule, policy, message)
    _faults.point("train.health.triage", rule=rule, policy=policy)
    if level in (1, 2):     # snapshot: a post-mortem without dying
        # (the raise level skips this — the escaping AnomalyError gets
        # its crash report from the existing guards, and a second dump
        # here would break their per-exception dedupe)
        try:
            _flightrec.dump_crash(
                where=f"train.health.{rule}",
                extra={"rule": rule, "policy": policy,
                       "message": message, "health": status()})
        except Exception:
            log.exception("train health: snapshot dump failed")
    if level >= 2:          # checkpoint: emergency commit, async writer
        mod = module if module is not None else _triage_module()
        mgr = getattr(mod, "_ckpt_manager", None)
        if mgr is not None:
            try:
                # still the async writer thread either way; the raise
                # path blocks on the commit because the fit loop's
                # mgr.wait() is never reached once AnomalyError flies
                seq = mgr.save(mod, epoch, nbatch, block=(level >= 3))
                _metrics.counter("train.health.emergency_ckpts").inc()
                _flightrec.note("train.health.ckpt", rule=rule, seq=seq,
                                epoch=epoch, nbatch=nbatch)
                if _core.enabled():
                    _core.event("train.health.ckpt", rule=rule, seq=seq,
                                epoch=epoch, nbatch=nbatch)
            except Exception:
                log.exception("train health: emergency checkpoint "
                              "failed; the last committed one stands")
        elif policy == "checkpoint":
            # raise-level commits are best-effort (a bare sentinel test
            # has no fit running); an explicit checkpoint policy with
            # nothing to commit through deserves the noise
            log.warning("train health: policy 'checkpoint' but no "
                        "checkpoint manager is bound; skipping the commit")
    if level >= 3:
        from .sentinel import AnomalyError
        raise AnomalyError(f"training health rule {rule!r}: {message}")


# -------------------------------------------------------------- monitor
class _Series:
    """One stat series: EMA baseline + rolling window with a cached
    median/MAD threshold pair (recomputed every ``_THRESH_EVERY``
    appends — the stepattr straggler-detector discipline)."""

    __slots__ = ("window", "ema", "alpha", "_sorted_at", "_med", "_mad",
                 "appends")

    def __init__(self, maxlen):
        self.window = collections.deque(maxlen=maxlen)
        self.ema = None
        self.alpha = 2.0 / (maxlen + 1)
        self._sorted_at = -1
        self._med = None
        self._mad = None
        self.appends = 0

    def append(self, v):
        self.window.append(v)
        self.appends += 1
        self.ema = v if self.ema is None else \
            self.ema + self.alpha * (v - self.ema)

    def med_mad(self):
        """(median, MAD with floors), or None during warm-up."""
        if len(self.window) < _MIN_SAMPLES:
            return None
        if self._med is None or \
                self.appends - self._sorted_at >= _THRESH_EVERY:
            win = sorted(self.window)
            med = win[len(win) // 2]
            mad = sorted([abs(w - med) for w in win])[len(win) // 2]
            self._med = med
            self._mad = max(mad, _MAD_FLOOR_FRAC * abs(med),
                            _MAD_FLOOR_ABS)
            self._sorted_at = self.appends
        return self._med, self._mad


_finite = math.isfinite


class HealthMonitor:
    """Deterministic detectors over the in-program stat stream.

    Parameters (each defaulting from its env knob where one exists):

    window : int — rolling window per series
        (``MXNET_TRAIN_HEALTH_WINDOW``, default 64).
    k_mad : float — spike/explosion threshold multiplier: value >
        median + k·MAD fires (``MXNET_TRAIN_HEALTH_K``, default 6).
    policy : str | dict — ladder policy: one name for every rule, or a
        per-rule dict; unset rules resolve through
        ``MXNET_TRAIN_HEALTH_POLICY`` (see :func:`resolve_policy`).
    plateau_tol : float — relative EMA movement under which a loss
        observation counts as flat (default 1e-4).
    ratio_band : (low, high) — healthy ‖Δw‖/‖w‖ band (default
        (1e-8, 0.5)). The ratio is read once per dispatch window, over
        the window-wide delta: with a K-step scan it covers K updates,
        so size the band for the windowed step, not a single one.
    collapse_frac : float — grad_norm < frac·median fires
        ``grad_collapse`` (default 0.01).
    cooldown : int — observations a fired rule holds down before it can
        fire again (default: the window size) — bounds record volume.
    """

    def __init__(self, window=None, k_mad=None, policy=None,
                 plateau_tol=1e-4, ratio_band=(1e-8, 0.5),
                 collapse_frac=0.01, cooldown=None):
        self.window = max(_MIN_SAMPLES,
                          _env_int("MXNET_TRAIN_HEALTH_WINDOW", 64)
                          if window is None else int(window))
        self.k_mad = _env_float("MXNET_TRAIN_HEALTH_K", 6.0) \
            if k_mad is None else float(k_mad)
        if isinstance(policy, str):
            self._policy = {r: policy for r in RULES}
        else:
            self._policy = dict(policy or {})
        self.plateau_tol = float(plateau_tol)
        self.ratio_band = (float(ratio_band[0]), float(ratio_band[1]))
        self.collapse_frac = float(collapse_frac)
        self.cooldown = self.window if cooldown is None else int(cooldown)
        self._series = {"loss": _Series(self.window),
                        "grad_norm": _Series(self.window),
                        "update_ratio": _Series(self.window)}
        self._flat_run = 0              # consecutive flat-loss steps
        self._last_fired = {}           # rule -> observation index
        self._first_fired = {}          # rule -> observation index
        self._firings = collections.deque(maxlen=256)
        self._gauges = None
        self._loss_gauges = {}          # head index -> cached handle
        self._gauges_gen = -1
        self.observations = 0
        self.state = 0

    # ------------------------------------------------------------ wiring
    def policy_for(self, rule):
        return resolve_policy(rule, self._policy.get(rule))

    def _handles(self):
        """Cached metric handles, refreshed on registry reset (the
        stepattr phase-histogram idiom — registry lookups take a lock
        each and observe() sits on the boundary path)."""
        gen = _metrics.generation()
        if self._gauges is None or self._gauges_gen != gen:
            self._gauges = {
                "state": _metrics.gauge("train.health.state"),
                **{s: _metrics.gauge(f"train.health.{s}")
                   for s in ("grad_norm", "param_norm", "update_ratio")},
            }
            self._loss_gauges = {}
            self._gauges_gen = gen
        return self._gauges

    def _loss_gauge(self, head):
        g = self._loss_gauges.get(head)
        if g is None:
            g = _metrics.gauge("train.health.loss", head=str(head))
            self._loss_gauges[head] = g
        return g

    def firings(self):
        return list(self._firings)

    def series(self):
        out = {name: list(s.window) for name, s in self._series.items()}
        out["ema"] = {name: s.ema for name, s in self._series.items()
                      if s.ema is not None}
        return out

    # ------------------------------------------------------------ observe
    def observe(self, stats, epoch=0, nbatch=0):
        """Ingest one step's stat dict — ``grad_norm``, ``param_norm``,
        ``update_ratio``, ``nonfinite`` scalars plus a ``loss`` head
        list — run every rule, and emit metrics/ring/trace records for
        each firing. Returns the firing dicts (rule, policy, message,
        value, threshold) for the caller's triage pass; the ladder
        itself runs in :func:`escalate` (the fit loop owns the module
        handle the checkpoint level needs)."""
        self.observations += 1
        n = self.observations
        gn = float(stats.get("grad_norm", 0.0))
        pn = float(stats.get("param_norm", 0.0))
        ur = float(stats.get("update_ratio", 0.0))
        heads = [float(v) for v in (stats.get("loss") or ())]
        loss = sum(heads) if heads else None
        nonfinite = float(stats.get("nonfinite", 0.0)) >= 0.5 or \
            not (_finite(gn) and _finite(pn) and
                 all(_finite(h) for h in heads))

        g = self._handles()
        g["grad_norm"].set(gn)
        g["param_norm"].set(pn)
        g["update_ratio"].set(ur)
        for i, h in enumerate(heads):
            self._loss_gauge(i).set(h)

        fired = []

        def fire(rule, value, threshold, why):
            last = self._last_fired.get(rule)
            if last is not None and n - last <= self.cooldown:
                return
            self._last_fired[rule] = n
            self._first_fired.setdefault(rule, n)
            fired.append({"rule": rule, "policy": self.policy_for(rule),
                          "value": value, "threshold": threshold,
                          "epoch": epoch, "nbatch": nbatch, "n": n,
                          "message": why})

        # --- detectors (all deterministic; MAD floors per stepattr) ---
        ls = self._series["loss"]
        if loss is not None and _finite(loss):
            mm = ls.med_mad()
            if mm is not None and loss > mm[0] + self.k_mad * mm[1]:
                fire("loss_spike", loss, mm[0] + self.k_mad * mm[1],
                     f"loss {loss:.6g} > median {mm[0]:.6g} + "
                     f"{self.k_mad:g}*MAD {mm[1]:.6g}")
            prev_ema = ls.ema
            if prev_ema is not None and abs(loss - prev_ema) <= \
                    self.plateau_tol * max(abs(prev_ema), 1e-12):
                self._flat_run += 1
                if self._flat_run == self.window:
                    fire("loss_plateau", loss, prev_ema,
                         f"loss flat within {self.plateau_tol:g} of its "
                         f"EMA for {self.window} steps")
                    self._flat_run = 0
            else:
                self._flat_run = 0
            ls.append(loss)

        gs = self._series["grad_norm"]
        if _finite(gn):
            mm = gs.med_mad()
            if mm is not None:
                hi = mm[0] + self.k_mad * mm[1]
                if gn > hi:
                    fire("grad_explosion", gn, hi,
                         f"grad norm {gn:.6g} > median {mm[0]:.6g} + "
                         f"{self.k_mad:g}*MAD {mm[1]:.6g}")
                elif mm[0] > 0 and gn < self.collapse_frac * mm[0]:
                    fire("grad_collapse", gn, self.collapse_frac * mm[0],
                         f"grad norm {gn:.6g} < {self.collapse_frac:g}*"
                         f"median {mm[0]:.6g}")
            gs.append(gn)

        if _finite(ur):
            lo, hi = self.ratio_band
            if ur > hi:
                fire("update_ratio_high", ur, hi,
                     f"update ratio {ur:.6g} above band {hi:g}")
            elif 0.0 < lo and ur < lo and gn > 0.0:
                fire("update_ratio_low", ur, lo,
                     f"update ratio {ur:.6g} below band {lo:g}")
            self._series["update_ratio"].append(ur)

        if nonfinite:
            fire("nonfinite", 1.0, 0.5,
                 "non-finite value in the step stats "
                 f"(grad_norm={gn!r}, loss={loss!r})")

        for f in fired:
            self._emit(f)
        g["state"].set(self.state)
        return fired

    # ----------------------------------------------------------- emission
    def _emit(self, f):
        """One firing -> metric + flight-ring record (with the full stat
        window) + trace-plane event + state advance. The triage ladder
        runs separately in :func:`escalate`."""
        rule = f["rule"]
        self.state = max(self.state, _SEVERITY.get(rule, 1))
        self._firings.append(f)
        _metrics.counter("train.health.firings", rule=rule).inc()
        _metrics.gauge("train.health.rule_fired", rule=rule).set(f["n"])
        _metrics.gauge("train.health.first_firing",
                       rule=rule).set(self._first_fired[rule])
        tid = _trace.current_id()
        ring = {"rule": rule, "policy": f["policy"], "epoch": f["epoch"],
                "nbatch": f["nbatch"], "value": f["value"],
                "threshold": f["threshold"],
                "window": {name: [round(v, 8) for v in s.window]
                           for name, s in self._series.items()}}
        if tid:
            ring["trace"] = tid
        _flightrec.note("train.health", **ring)
        if _core.enabled():
            _core.event("train.health", rule=rule, policy=f["policy"],
                        epoch=f["epoch"], nbatch=f["nbatch"],
                        value=f["value"], threshold=f["threshold"])
        if tid:
            now = time.perf_counter()
            _trace.record(tid, f"train.health.{rule}", now, now,
                          policy=f["policy"], value=f["value"])


def reset():
    """Drop the process monitor, its state, and the triage binding (the
    arming override survives, like stepattr's — tests clear it
    explicitly via ``configure(armed=None)``)."""
    global _monitor, _triage
    with _lock:
        _monitor = None
    _triage = None
