"""Prometheus text-format exporter (exposition format 0.0.4).

Renders the metrics registry as the plain-text scrape format: counters
get a ``_total`` suffix, histograms expand into cumulative ``_bucket``
series plus ``_sum``/``_count``, metric names are sanitized to the
``[a-zA-Z_:][a-zA-Z0-9_:]*`` grammar (dots become underscores) and
every family is prefixed ``mxnet_`` so a co-scraped process namespace
stays clean. ``parse()`` reads the same format back — the round-trip
used by the tests and by tools/parse_log.py.
"""
from __future__ import annotations

import os
import re

from . import metrics as _metrics

__all__ = ["render", "dump", "parse", "sanitize"]

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

PREFIX = "mxnet_"


def sanitize(name):
    """Metric-family name in Prometheus grammar, ``mxnet_`` prefixed."""
    s = _NAME_OK.sub("_", name)
    if not s.startswith(PREFIX):
        s = PREFIX + s
    return s


def _labels_text(labels, extra=None):
    items = list(labels) + list(extra or [])
    if not items:
        return ""
    inner = ",".join(f'{_NAME_OK.sub("_", k)}="{v}"' for k, v in items)
    return "{" + inner + "}"


def _fmt(v):
    if v is None:
        return "NaN"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render(openmetrics=False, fleet=None):
    """The registry as exposition text.

    ``openmetrics=True`` additionally emits histogram exemplars in the
    OpenMetrics form — ``..._bucket{le="0.1"} 5 # {trace_id="t00002a"}
    0.093`` — on the buckets that carry one. The default (plain
    Prometheus 0.0.4 text) is byte-identical to the pre-exemplar
    format: scrapers and ``parse()`` never see the annotation unless
    asked for (the trace-plane golden-output test pins this).

    ``fleet=`` takes a merged fleet snapshot (``telemetry.fleet.merge``)
    and renders *that* instead of the live registry: one exposition
    text with a ``rank`` label on every sample, per-rank and lossless
    (sums/quantiles are the scraper's aggregation to make). The default
    single-process rendering is untouched.
    """
    if fleet is not None:
        return _render_fleet(fleet, openmetrics)
    lines = []
    seen_types = set()

    def header(fam, typ):
        if fam not in seen_types:
            lines.append(f"# TYPE {fam} {typ}")
            seen_types.add(fam)

    for m in _metrics.all_metrics():
        fam = sanitize(m.name)
        if isinstance(m, _metrics.Counter):
            fam += "_total"
            header(fam, "counter")
            lines.append(f"{fam}{_labels_text(m.labels)} {_fmt(m.value)}")
        elif isinstance(m, _metrics.Gauge):
            header(fam, "gauge")
            lines.append(f"{fam}{_labels_text(m.labels)} {_fmt(m.value)}")
        elif isinstance(m, _metrics.Histogram):
            header(fam, "histogram")

            def _ex(idx):
                if not openmetrics:
                    return ""
                ex = m.exemplars.get(idx)
                if ex is None:
                    return ""
                return f' # {{trace_id="{ex[0]}"}} {_fmt(ex[1])}'

            for i, (le, c) in enumerate(m.cumulative()):
                lines.append(
                    f"{fam}_bucket"
                    f"{_labels_text(m.labels, [('le', _fmt(le))])} {c}"
                    f"{_ex(i)}")
            lines.append(
                f"{fam}_bucket"
                f"{_labels_text(m.labels, [('le', '+Inf')])} {m.count}"
                f"{_ex(len(m.buckets))}")
            lines.append(f"{fam}_sum{_labels_text(m.labels)} {_fmt(m.sum)}")
            lines.append(f"{fam}_count{_labels_text(m.labels)} {m.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def _render_fleet(fleet, openmetrics=False):
    """A merged fleet snapshot as one exposition text with rank labels."""
    lines = []
    seen_types = set()

    def header(fam, typ):
        if fam not in seen_types:
            lines.append(f"# TYPE {fam} {typ}")
            seen_types.add(fam)

    def ranked(slot):
        labels = sorted(slot["labels"].items())
        for r in sorted(slot["by_rank"], key=int):
            yield r, labels + [("rank", r)]

    for key in sorted(fleet.get("counters", {})):
        slot = fleet["counters"][key]
        fam = sanitize(slot["name"]) + "_total"
        header(fam, "counter")
        for r, labels in ranked(slot):
            lines.append(
                f"{fam}{_labels_text(labels)} "
                f"{_fmt(slot['by_rank'][r])}")
    for key in sorted(fleet.get("gauges", {})):
        slot = fleet["gauges"][key]
        fam = sanitize(slot["name"])
        header(fam, "gauge")
        for r, labels in ranked(slot):
            lines.append(
                f"{fam}{_labels_text(labels)} "
                f"{_fmt(slot['by_rank'][r])}")
    for key in sorted(fleet.get("histograms", {})):
        slot = fleet["histograms"][key]
        fam = sanitize(slot["name"])
        header(fam, "histogram")
        for r, labels in ranked(slot):
            rec = slot["by_rank"][r]
            exemplars = {int(i): ex
                         for i, ex in rec.get("exemplars", {}).items()}

            def _ex(idx):
                ex = exemplars.get(idx) if openmetrics else None
                if ex is None:
                    return ""
                return f' # {{trace_id="{ex[0]}"}} {_fmt(ex[1])}'

            for i, (le, c) in enumerate(zip(rec["buckets"],
                                            rec["bucket_counts"])):
                lines.append(
                    f"{fam}_bucket"
                    f"{_labels_text(labels, [('le', _fmt(le))])} {c}"
                    f"{_ex(i)}")
            lines.append(
                f"{fam}_bucket"
                f"{_labels_text(labels, [('le', '+Inf')])} "
                f"{rec['count']}{_ex(len(rec['buckets']))}")
            lines.append(
                f"{fam}_sum{_labels_text(labels)} {_fmt(rec['sum'])}")
            lines.append(
                f"{fam}_count{_labels_text(labels)} {rec['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def dump(path):
    """Write the exposition text; returns the path."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(render())
    return path


_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)\s*$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse(text):
    """Exposition text -> {series_key: float}, with series_key rendered
    exactly like ``Metric.key`` (name{k="v",...}) so round-trips compare
    structurally. ``# TYPE`` lines come back under the "__types__" key."""
    out = {}
    types = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"unparseable sample line: {line!r}")
        labels = sorted(_LABEL.findall(m.group("labels") or ""))
        key = m.group("name")
        if labels:
            key += "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
        out[key] = float(m.group("value"))
    out["__types__"] = types
    return out
