"""Base utilities: errors, attribute parsing, registry helpers.

TPU-native re-imagination of the reference's ctypes base layer
(reference: python/mxnet/base.py). There is no C-API boundary here —
the "backend" is JAX/XLA — so this module only carries the shared
error type and the string<->typed-attr codecs used by Symbol JSON
serialization (reference: src/c_api/c_api_symbolic.cc attr handling).
"""
from __future__ import annotations

import ast
import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types"]


class MXNetError(Exception):
    """Framework-level error (reference: MXGetLastError surface)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)


def attr_to_str(value):
    """Serialize a typed attr value to the string form used in symbol JSON.

    Mirrors the dmlc::Parameter string forms (reference:
    dmlc-core parameter.h): tuples as ``(2, 2)``, bools as ``True``/``False``,
    numbers via repr.
    """
    if isinstance(value, str):
        return value
    if isinstance(value, bool):
        return str(value)
    if isinstance(value, (tuple, list)):
        return "(" + ", ".join(attr_to_str(v) for v in value) + ")"
    if value is None:
        return "None"
    if isinstance(value, _np.dtype):
        return _np.dtype(value).name
    if isinstance(value, type):  # e.g. np.float32 class
        return _np.dtype(value).name
    return repr(value)


def str_to_attr(s):
    """Parse a string attr back into a typed python value (best effort)."""
    if not isinstance(s, str):
        return s
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def merge_shape(a, b):
    """Merge two partial shapes (None = unknown, 0 = unknown dim).

    The reference's shape convention (nnvm InferShape): dims merge
    pointwise, 0 yields to a known dim; conflicting known dims raise.
    """
    if a is None:
        return tuple(b) if b is not None else None
    if b is None:
        return tuple(a)
    if len(a) != len(b):
        raise MXNetError(f"incompatible shapes {a} vs {b}")
    out = []
    for x, y in zip(a, b):
        if x == 0:
            out.append(y)
        elif y == 0 or x == y:
            out.append(x)
        else:
            raise MXNetError(f"incompatible shapes {a} vs {b}")
    return tuple(out)


def shape_is_known(s):
    return s is not None and 0 not in s


def parse_tuple(val, length=None, name="param"):
    """Coerce ints / strings / sequences into an int tuple."""
    if val is None:
        return None
    if isinstance(val, str):
        val = str_to_attr(val)
    if isinstance(val, (int, _np.integer)):
        val = (int(val),) * (length or 1)
    val = tuple(int(v) for v in val)
    if length is not None and len(val) != length:
        raise ValueError(f"{name} expected length-{length} tuple, got {val}")
    return val


def parse_bool(val):
    if isinstance(val, str):
        return val.lower() in ("true", "1")
    return bool(val)


def parse_int(val):
    return int(val)


def parse_float(val):
    return float(val)
