"""Ready-order bucket scheduler for gradient synchronization.

The reference overlaps gradient reduction with backward compute through
its dependency engine: each layer's ZPush is enqueued the moment that
layer's gradient write completes, so ps-lite traffic for late layers
rides under the remaining backward ops (reference: kvstore_dist.h
ZPush + engine PushAsync ordering, and the DDP bucket design of Li et
al., VLDB 2020). This module is the TPU-native analog for
``KVStoreDistSync``: gradients are *staged* as they are pushed (in
reverse execution order — the order backward produces them), packed
into flat buckets, and each bucket's all-reduce is DISPATCHED the
moment the bucket fills — riding JAX async dispatch, so the collective
queues behind the still-running backward program instead of waiting
for a host sync. Nothing blocks until ``flush()`` (driven by ``pull``
or any state read), at which point the reduced values are scattered
back and applied in dispatch order.

Priorities finally mean something: ``push(priority=...)`` orders the
staging queue (higher = dispatched earlier), so a caller pushing
gradients as backward readiness dictates gets buckets on the wire in
that order.

Telemetry: ``kvstore.overlap.seconds`` accumulates, per bucket, the
window between dispatch and the flush that consumed it — collective
time that ran hidden behind other work; ``kvstore.exposed.seconds``
accumulates the residual host wait at flush. Per-bucket dispatch/apply
records land in the flight-recorder ring, and ``bucket_log`` keeps the
most recent per-bucket timings for benchmarks
(benchmarks/comm_overlap.py computes the exposed-comm fraction and the
max number of buckets in flight from it).
"""
from __future__ import annotations

import collections
import time

import jax.numpy as jnp

from . import telemetry as _telemetry

__all__ = ["BucketScheduler"]


class _Bucket:
    __slots__ = ("entries", "raw", "dtype", "nbytes", "reduced",
                 "dispatch_t", "seq")

    def __init__(self, dtype, seq):
        self.entries = []        # (key, ctx, jnp array) in staging order
        self.raw = []            # original pending entries (for re-queue)
        self.dtype = dtype
        self.nbytes = 0
        self.reduced = None      # lazy flat result once dispatched
        self.dispatch_t = None
        self.seq = seq


class BucketScheduler:
    """Stage -> bucket -> async dispatch -> ordered apply.

    Parameters
    ----------
    reduce_flat : callable(jnp 1-D array) -> jnp 1-D array
        The collective; must dispatch asynchronously (jax native).
    apply_fn : callable(key, ctx, reduced jnp array)
        Consumer of each key's reduced value, run at flush in dispatch
        order (the kvstore updater / store assignment).
    bucket_bytes_fn : callable() -> int
        Bucket capacity, read per staging round (env-tunable).
    """

    def __init__(self, reduce_flat, apply_fn, bucket_bytes_fn):
        self._reduce = reduce_flat
        self._apply = apply_fn
        self._bucket_bytes = bucket_bytes_fn
        self._pending = []            # (prio, arrival, key, ctx, arr)
        self._arrival = 0
        self._staged = set()          # keys pending or in flight, unapplied
        self._inflight = []           # dispatched buckets, dispatch order
        self._seq = 0
        # recent per-bucket timings for benchmarks/diagnostics
        self.bucket_log = collections.deque(maxlen=1024)
        # order-audit trail for the static collective-order checker
        # (analysis rules CO301/DA204): which push call staged which key
        # at which priority, grouped by flush window. One dict append
        # per stage — negligible against the collective it schedules.
        self.stage_log = collections.deque(maxlen=1024)
        self._push_seq = 0            # distinct push() calls (arrival epochs)
        self._window = 0              # flush windows completed

    # ------------------------------------------------------------- staging
    def note_push_call(self):
        """Mark the start of one caller-level push(): entries staged
        under different push calls arrive in grad-ready order, which the
        collective-order analysis must treat as nondeterministic across
        workers (entries within one call share the caller's key order)."""
        self._push_seq += 1

    def stage(self, key, ctx, arr, priority=0):
        """Queue one key's merged gradient; dispatches any bucket the
        staging completes. A re-push of a still-unapplied key first
        flushes (two pushes of one key are two logical reductions)."""
        if key in self._staged:
            self.flush()
        self._staged.add(key)
        self._pending.append((priority, self._arrival, key, ctx, arr))
        self._arrival += 1
        self.stage_log.append({"key": key, "prio": priority,
                               "push": self._push_seq,
                               "buf": id(arr), "window": self._window})
        self._cut_buckets(dispatch_partial=False)

    def _cut_buckets(self, dispatch_partial):
        """Walk the pending queue in priority order, packing same-dtype
        flat buckets up to capacity. Full buckets dispatch immediately;
        partial ones dispatch only when ``dispatch_partial`` (flush),
        otherwise their entries return to pending untouched."""
        if not self._pending:
            return
        cap = self._bucket_bytes()
        # higher priority first; stable on arrival so a caller pushing
        # in backward-ready order keeps that order within a priority
        self._pending.sort(key=lambda e: (-e[0], e[1]))
        open_buckets = {}             # dtype -> _Bucket
        leftover = []
        for entry in self._pending:
            _, _, key, ctx, arr = entry
            a = jnp.asarray(arr)
            sz = int(a.size) * a.dtype.itemsize
            b = open_buckets.get(a.dtype)
            if b is not None and b.nbytes + sz > cap:
                self._dispatch(b)
                b = None
            if b is None:
                b = open_buckets[a.dtype] = _Bucket(a.dtype, self._seq)
                self._seq += 1
            b.entries.append((key, ctx, a))
            b.raw.append(entry)
            b.nbytes += sz
            if b.nbytes >= cap:
                self._dispatch(b)
                del open_buckets[a.dtype]
        for b in open_buckets.values():
            if dispatch_partial:
                self._dispatch(b)
            else:
                leftover.extend(b.raw)
        self._pending = leftover

    def _dispatch(self, bucket):
        """One async collective for the bucket's concatenated payload."""
        arrs = [jnp.ravel(a) for _, _, a in bucket.entries]
        flat = arrs[0] if len(arrs) == 1 else jnp.concatenate(arrs)
        bucket.reduced = self._reduce(flat)
        bucket.dispatch_t = time.perf_counter()
        if _telemetry.enabled():
            _telemetry.counter("kvstore.bucket.dispatched").inc()
            _telemetry.counter("kvstore.allreduce.bytes").inc(bucket.nbytes)
        _telemetry.flightrec.note(
            "kvstore.bucket.dispatch", seq=bucket.seq,
            keys=len(bucket.entries), bytes=bucket.nbytes)
        self._inflight.append(bucket)

    # --------------------------------------------------------------- flush
    def in_flight(self):
        """Dispatched-but-unapplied bucket count (diagnostics)."""
        return len(self._inflight)

    def drop_pending(self):
        """Discard everything staged or in flight WITHOUT applying it —
        the abort teardown (kvstore.close(abort=True)) for a store whose
        collective is already broken by a dead peer: a flush would
        re-enter the failed all-reduce, and the gradients of the batch
        being abandoned are no longer wanted anyway. Returns the number
        of entries dropped."""
        n = len(self._pending) + sum(len(b.entries)
                                     for b in self._inflight)
        self._pending = []
        self._inflight = []
        self._staged.clear()
        self._window += 1
        return n

    def flush(self):
        """Dispatch what remains pending, then apply every in-flight
        bucket's reduced values in dispatch order."""
        self._cut_buckets(dispatch_partial=True)
        self._window += 1       # close the audit window for stage_log
        if not self._inflight:
            self._staged.clear()
            return
        t_flush = time.perf_counter()
        telemetry_on = _telemetry.enabled()
        for b in self._inflight:
            t0 = time.perf_counter()
            red = b.reduced
            try:
                red.block_until_ready()
            except AttributeError:
                pass                      # non-jax stub in tests
            t1 = time.perf_counter()
            hidden = max(0.0, t_flush - b.dispatch_t)
            exposed = t1 - t0
            if telemetry_on:
                _telemetry.counter("kvstore.overlap.seconds").inc(hidden)
                _telemetry.counter("kvstore.exposed.seconds").inc(exposed)
            _telemetry.flightrec.note(
                "kvstore.bucket.apply", seq=b.seq, keys=len(b.entries),
                hidden_us=int(hidden * 1e6), exposed_us=int(exposed * 1e6))
            self.bucket_log.append({
                "seq": b.seq, "keys": len(b.entries), "bytes": b.nbytes,
                "key_ids": [k for k, _, _ in b.entries],
                "dispatch_t": b.dispatch_t, "apply_t": t1,
                "hidden_s": hidden, "exposed_s": exposed})
            off = 0
            for key, ctx, a in b.entries:
                n = int(a.size)
                self._apply(key, ctx, red[off:off + n].reshape(a.shape))
                off += n
        self._inflight = []
        self._staged.clear()
