"""Deployment surface: ahead-of-time export + standalone predictor.

Reference counterpart: the C predict API
(reference: src/c_api/c_predict_api.cc:1-334,
include/mxnet/c_predict_api.h:1-210) — ``MXPredCreate(symbol_json,
param_bytes, input_shapes)`` builds a self-contained inference executor
from serialized artifacts, ``MXPredForward``/``MXPredGetOutput`` run it;
the amalgamation build ships exactly this surface for serving/mobile.

TPU-native realization: ``export_model`` traces the bound inference
graph once and serializes the compiled program via ``jax.export``
(StableHLO, multi-platform cpu+tpu) into a single ``.mxp`` archive
together with the reference-format ``.params`` bytes and a JSON
manifest. ``Predictor`` loads the archive and runs it WITHOUT the
Symbol/Module stack: no graph rebuilding, no re-tracing, no
initializers — deserialize, bind params, call. Shapes are fixed at
export time (the reference's MXPredReshape analog is re-exporting at
the new shapes).

Round-trip contract (tests/test_predict.py): Predictor outputs ==
``Module.predict`` outputs for the same params, including from a fresh
process that never touches mx.sym/mx.mod.
"""
from __future__ import annotations

import json
import os
import tempfile
import zipfile

import numpy as np

from .base import MXNetError

_FORMAT_VERSION = 1
_PROGRAM = "program.stablehlo"
_MANIFEST = "manifest.json"
_PARAMS = "weights.params"


def export_model(path, symbol, arg_params, aux_params, data_shapes,
                 compute_dtype=None, data_dtypes=None, quantize=None):
    """Serialize an inference program for ``symbol`` to ``path``.

    ``data_shapes``: dict input name -> shape (the non-parameter inputs,
    like MXPredCreate's input_shapes). ``arg_params``/``aux_params``:
    trained parameters (NDArray or array-like). ``compute_dtype``:
    optional mixed-precision compute dtype (e.g. jnp.bfloat16) baked
    into the exported program. ``data_dtypes``: dict input name ->
    dtype (default float32) — recorded per input in the manifest and
    baked into the exported program's input avals, so bf16/int inputs
    (embedding ids, token streams) round-trip through the artifact.
    ``quantize="int8"`` / ``"fp8"``: post-training per-channel weight
    quantization at export — the graph's dense/conv weights are
    captured in the narrow storage dtype (int8 or float8_e4m3fn) +
    per-channel f32 scales (``ops/quant.py``) and the artifact embeds
    the quantized graph, so the ``.mxp`` ships ~4x smaller weights and
    the serving tier can pin quantized rungs; outputs stay within
    ``quant.INT8_TOL`` / ``quant.FP8_TOL`` of the float export.
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexport
    from .executor import _build_graph_runner
    from .ndarray import NDArray, save as nd_save

    quantized_weights = []
    if quantize is not None:
        from .ops import quant as _quant
        n_before = set(arg_params)
        symbol, arg_params = _quant.quantize_symbol(symbol, arg_params,
                                                    dtype=quantize)
        quantized_weights = sorted(n_before - set(arg_params))

    data_shapes = {k: tuple(v) for k, v in data_shapes.items()}
    data_dtypes = {k: np.dtype(
        (data_dtypes or {}).get(k, np.float32)) for k in data_shapes}
    runner, arg_names, aux_names, _ = _build_graph_runner(
        symbol, compute_dtype=compute_dtype)
    param_names = [n for n in arg_names
                   if n not in data_shapes and n in arg_params]
    # declared-but-untrained inputs (loss-head labels) are zero-filled,
    # exactly like Module.predict's unbound labels; their shapes come
    # from inference against the data shapes
    zero_names = [n for n in arg_names
                  if n not in data_shapes and n not in arg_params]
    zeros = {}
    if zero_names:
        inferred, _, _ = symbol.infer_shape(**data_shapes)
        by_name = dict(zip(symbol.list_arguments(), inferred))
        for n in zero_names:
            s = by_name.get(n)
            if s is None:
                raise MXNetError(
                    f"export_model: no params and no inferable shape "
                    f"for input {n!r}")
            zeros[n] = jnp.zeros(s, jnp.float32)

    def _val(p):
        return p.asjax() if isinstance(p, NDArray) else jnp.asarray(p)

    params = {n: _val(arg_params[n]) for n in param_names}
    params.update(zeros)
    param_names = param_names + zero_names
    # aux entries with no trained value (a decoder's KV-cache arrays +
    # cursor) zero-fill at their inferred shapes and declared dtypes —
    # the empty cache IS the correct exported snapshot
    aux_params = aux_params or {}
    aux = {}
    if any(n not in aux_params for n in aux_names):
        _, _, aux_shapes = symbol.infer_shape(**data_shapes)
        aux_shape_by_name = dict(zip(symbol.list_auxiliary_states(),
                                     aux_shapes))
        aux_dtype_by_name = {
            n.name: np.dtype(n._extra["__dtype__"])
            for n in symbol._topo_nodes()
            if n.is_variable and n._extra.get("__is_aux__")
            and n._extra.get("__dtype__")}
    for n in aux_names:
        if n in aux_params:
            aux[n] = _val(aux_params[n])
        else:
            s = aux_shape_by_name.get(n)
            if s is None:
                raise MXNetError(
                    f"export_model: no value and no inferable shape "
                    f"for aux state {n!r}")
            aux[n] = jnp.zeros(
                s, aux_dtype_by_name.get(n, np.float32))

    # stateful-inference graphs (KV-cache decoders): the exported
    # program must RETURN the advanced aux so the Predictor can carry
    # the cache between calls — jax.export has no mutable state
    stateful = any(
        not n.is_variable
        and getattr(n.opdef(), "stateful_infer", False)
        for n in symbol._topo_nodes())

    def infer(params, aux, data):
        args = {**params, **data}
        outs, new_aux = runner(args, aux, False, jax.random.PRNGKey(0))
        if stateful:
            return outs, {**aux, **new_aux}
        return outs

    data_example = {n: jnp.zeros(s, data_dtypes[n])
                    for n, s in data_shapes.items()}
    exported = jexport.export(
        jax.jit(infer), platforms=("cpu", "tpu"))(params, aux,
                                                  data_example)
    blob = exported.serialize()

    manifest = {
        "format_version": _FORMAT_VERSION,
        "inputs": {n: list(s) for n, s in data_shapes.items()},
        "input_dtypes": {n: dt.name for n, dt in data_dtypes.items()},
        "param_names": param_names,
        "aux_names": aux_names,
        "output_names": symbol.list_outputs(),
        "compute_dtype": None if compute_dtype is None else
        np.dtype(compute_dtype).name,
        "quantize": quantize,
        "quantized_weights": quantized_weights,
        "stateful": stateful,
    }

    with tempfile.TemporaryDirectory() as td:
        pfile = os.path.join(td, "w.params")
        nd_save(pfile, {**{f"arg:{n}": NDArray(v)
                           for n, v in params.items()},
                        **{f"aux:{n}": NDArray(v)
                           for n, v in aux.items()}})
        with zipfile.ZipFile(path, "w") as z:
            z.writestr(_MANIFEST, json.dumps(manifest, indent=1))
            z.writestr(_PROGRAM, bytes(blob))
            z.write(pfile, _PARAMS)
    return path


class Predictor:
    """Load-and-run inference from an exported ``.mxp`` artifact.

    API mirrors the reference predict API's create/forward/get_output
    cycle (c_predict_api.h: MXPredCreate, MXPredSetInput/Forward,
    MXPredGetOutput). Only the array container and the deserialized
    program are touched — never the Symbol/Module stack.
    """

    def __init__(self, path, device=None):
        import jax
        from jax import export as jexport
        from .ndarray import load as nd_load

        self._path = path       # serve/warm.py re-registers from it
        with zipfile.ZipFile(path) as z:
            self._manifest = json.loads(z.read(_MANIFEST))
            if self._manifest["format_version"] != _FORMAT_VERSION:
                raise MXNetError(
                    f"unsupported artifact version "
                    f"{self._manifest['format_version']}")
            blob = z.read(_PROGRAM)
            with tempfile.TemporaryDirectory() as td:
                pfile = os.path.join(td, "w.params")
                with open(pfile, "wb") as f:
                    f.write(z.read(_PARAMS))
                loaded = nd_load(pfile)
        self._exported = jexport.deserialize(bytearray(blob))
        dev = device.jax_device() if hasattr(device, "jax_device") else \
            device
        if dev is None:
            dev = jax.devices()[0]

        def put(arr):
            return jax.device_put(arr.asjax(), dev)

        self._params = {n: put(loaded[f"arg:{n}"])
                        for n in self._manifest["param_names"]}
        self._aux = {n: put(loaded[f"aux:{n}"])
                     for n in self._manifest["aux_names"]}
        # stateful artifacts (KV-cache decoders) advance their aux per
        # forward; keep the as-exported snapshot for reset_state()
        self._aux0 = dict(self._aux) if self.stateful else None
        self._outputs = None

    @property
    def output_names(self):
        return list(self._manifest["output_names"])

    @property
    def input_shapes(self):
        return {n: tuple(s) for n, s in self._manifest["inputs"].items()}

    @property
    def quantize(self):
        """The artifact's PTQ mode (``"int8"`` / ``"fp8"``) or None for
        float exports (pre-quantization artifacts included)."""
        return self._manifest.get("quantize")

    @property
    def stateful(self):
        """True for stateful-inference artifacts (KV-cache decoders):
        each ``forward`` advances the carried aux state (the cache);
        ``reset_state()`` rewinds to the exported snapshot."""
        return bool(self._manifest.get("stateful"))

    def reset_state(self):
        """Rewind a stateful artifact's carried aux (the KV cache) to
        its exported snapshot. No-op for stateless artifacts."""
        if self._aux0 is not None:
            self._aux = dict(self._aux0)

    def reset_slot(self, slot):
        """Slot-pooled decode artifacts (``get_decode_symbol(
        per_slot=True)`` exports): rewind ONE slot's cache cursors to
        the exported snapshot, leaving every other slot's in-flight
        state untouched — the join seam of continuous batching, with no
        Symbol/Module stack in the process. Cursor aux cells are the
        ``*cache_pos`` entries (the ``attention_decode`` contract); the
        cache rows need no reset because positions beyond a slot's
        cursor carry exactly zero attention weight. No-op for stateless
        artifacts."""
        if self._aux0 is None:
            return
        for n, snap in self._aux0.items():
            if n.endswith("cache_pos") and snap.ndim == 2:
                self._aux[n] = self._aux[n].at[int(slot)].set(
                    snap[int(slot)])

    @property
    def input_dtypes(self):
        """Per-input dtypes recorded at export time (manifest
        ``input_dtypes``; float32 for pre-dtype artifacts)."""
        recorded = self._manifest.get("input_dtypes") or {}
        return {n: np.dtype(recorded.get(n, "float32"))
                for n in self._manifest["inputs"]}

    def forward(self, **inputs):
        """Run the exported program; returns the output list.

        Inputs are cast to the manifest's recorded per-input dtype (the
        exported program's input avals) — a bf16-exported model takes
        float32 host arrays, an embedding model takes integer ids.
        """
        import jax.numpy as jnp
        from .ndarray import NDArray

        dtypes = self.input_dtypes
        data = {}
        for n, shape in self.input_shapes.items():
            if n not in inputs:
                raise MXNetError(f"missing input {n!r}")
            v = inputs[n]
            v = v.asjax() if isinstance(v, NDArray) else jnp.asarray(v)
            if v.dtype != dtypes[n]:
                v = v.astype(dtypes[n])
            if tuple(v.shape) != shape:
                raise MXNetError(
                    f"input {n!r}: shape {tuple(v.shape)} != exported "
                    f"{shape} (re-export to reshape, like MXPredReshape)")
            data[n] = v
        res = self._exported.call(self._params, self._aux, data)
        if self.stateful:
            outs, new_aux = res
            self._aux = dict(new_aux)
        else:
            outs = res
        self._outputs = [NDArray(o) for o in outs]
        return self._outputs

    def batch_forward(self, **inputs):
        """Forward with a DYNAMIC leading batch dim.

        The exported program's batch size is fixed; this accepts any
        number of rows, runs them through the program in exported-batch
        windows — the tail window zero-padded with the serving pad
        helper (serve.batching.pad_rows) and sliced back afterwards
        (bit-transparent, same contract as the server's batcher) — and
        returns outputs with the caller's row count. One host->device
        staging per window, not per call-site array.
        """
        from .ndarray import NDArray
        from .serve.batching import pad_rows, slice_rows

        shapes = self.input_shapes
        dtypes = self.input_dtypes
        batch = next(iter(shapes.values()))[0]
        vals, rows = {}, None
        for n, shape in shapes.items():
            if n not in inputs:
                raise MXNetError(f"missing input {n!r}")
            if shape[0] != batch:
                raise MXNetError(
                    "batch_forward needs a common exported batch dim; "
                    f"input {n!r} has {shape[0]} != {batch}")
            v = inputs[n]
            v = np.asarray(v.asnumpy() if isinstance(v, NDArray) else v,
                           dtype=dtypes[n])
            if tuple(v.shape[1:]) != shape[1:]:
                raise MXNetError(
                    f"input {n!r}: rows of shape {tuple(v.shape[1:])} != "
                    f"exported {shape[1:]}")
            if rows is None:
                rows = v.shape[0]
            elif v.shape[0] != rows:
                raise MXNetError("inputs disagree on the row count")
            vals[n] = v
        if not rows:
            raise MXNetError("batch_forward needs at least one row")

        per_window = []
        for off in range(0, rows, batch):
            n_valid = min(batch, rows - off)
            window = {n: pad_rows(v[off:off + n_valid], batch)
                      for n, v in vals.items()}
            outs = self.forward(**window)
            per_window.append(slice_rows(outs, 0, n_valid))
        merged = []
        for i in range(len(per_window[0])):
            if len(per_window) == 1:
                merged.append(per_window[0][i])
            else:
                merged.append(NDArray(np.concatenate(
                    [w[i].asnumpy() for w in per_window], axis=0)))
        self._outputs = merged
        return merged

    def get_output(self, index=0):
        """reference: MXPredGetOutput — output of the last forward."""
        if self._outputs is None:
            raise MXNetError("call forward() first")
        return self._outputs[index]
