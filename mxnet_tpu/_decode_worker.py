"""Standalone JPEG decode/augment worker process.

The multiprocess analog of the reference's OMP-parallel RecordIO parser
(reference: src/io/iter_image_recordio_2.cc:28-595 — each OMP thread
decodes+augments a chunk of records into a preallocated output block).
Here each *process* owns a file handle on the ``.rec`` pack, receives
``(slot, [frame offsets])`` work orders on stdin, and writes decoded
float32 CHW images + labels into a shared-memory staging slot — so the
parent's per-batch cost is one memcpy, and decode throughput scales
with cores instead of fighting the GIL.

This file is deliberately self-contained (numpy + cv2 + stdlib only)
and is executed BY PATH (``python .../_decode_worker.py cfg.json``),
never imported: importing ``mxnet_tpu`` would initialize JAX (and, on a
real host, grab the TPU client) in every data worker. The RecordIO
framing it reads is the byte-stable container format
(recordio.py: [magic:4][lrec:4][payload][pad4], IRHeader "IfQQ") — the
same bytes the reference's dmlc-core reader consumes.

Augmentation implements the param-driven fast path of CreateAugmenter
(resize_short -> random/center/random-sized crop -> mirror -> cast ->
mean/std normalize), matching image.py's per-augmenter semantics.
Closure-based custom aug lists fall back to the in-process thread pool.
"""
import json
import os
import struct
import sys

# Executed BY PATH, so sys.path[0] is this package directory — scrub it
# before any further import, or stdlib modules shadowed by framework
# files resolve wrongly and kill the worker (observed: shared_memory ->
# secrets -> `import random` landing on mxnet_tpu/random.py, which then
# pulls JAX into the decode worker and dies mid-import).
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path[:] = [p for p in sys.path
               if os.path.abspath(p or os.getcwd()) != _HERE]

from multiprocessing import shared_memory

import numpy as np

_K_MAGIC = 0xced7230a
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def _decode_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


def _read_record(f, offset):
    """Read one record's payload given its frame-start offset."""
    f.seek(offset)
    head = f.read(8)
    magic, lrec = struct.unpack("<II", head)
    if magic != _K_MAGIC:
        raise ValueError(f"bad RecordIO magic at {offset}")
    _, length = _decode_lrec(lrec)
    return f.read(length)


def _unpack(payload):
    flag, label, _id, _id2 = struct.unpack(_IR_FORMAT, payload[:_IR_SIZE])
    body = payload[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(body[:flag * 4], dtype=np.float32)
        body = body[flag * 4:]
    return label, body


class Augmenter:
    """Param-driven augment chain (CreateAugmenter fast path)."""

    def __init__(self, cfg, rng):
        self.resize = int(cfg.get("resize", 0))
        self.rand_crop = bool(cfg.get("rand_crop", False))
        self.rand_resize = bool(cfg.get("rand_resize", False))
        self.rand_mirror = bool(cfg.get("rand_mirror", False))
        self.min_area = float(cfg.get("min_area", 0.3))
        self.ratio = tuple(cfg.get("ratio", (3 / 4.0, 4 / 3.0)))
        self.inter = int(cfg.get("inter", 2))
        self.mean = np.asarray(cfg["mean"], np.float32) \
            if cfg.get("mean") is not None else None
        self.std = np.asarray(cfg["std"], np.float32) \
            if cfg.get("std") is not None else None
        self.rng = rng

    def _resize(self, img, w, h):
        import cv2
        return cv2.resize(img, (w, h), interpolation=self.inter)

    def _resize_short(self, img):
        # integer arithmetic matches image.py _resize_short_np exactly
        h, w = img.shape[:2]
        if h > w:
            new_w, new_h = self.resize, self.resize * h // w
        else:
            new_w, new_h = self.resize * w // h, self.resize
        return self._resize(img, new_w, new_h)

    def _crop(self, img, cw, ch):
        h, w = img.shape[:2]
        if self.rand_resize:
            area = h * w
            for _ in range(10):
                target = self.rng.uniform(self.min_area, 1.0) * area
                ar = self.rng.uniform(*self.ratio)
                nw = int(round(np.sqrt(target * ar)))
                nh = int(round(np.sqrt(target / ar)))
                if self.rng.random() < 0.5:
                    nw, nh = nh, nw
                if nw <= w and nh <= h:
                    x0 = self.rng.integers(0, w - nw + 1)
                    y0 = self.rng.integers(0, h - nh + 1)
                    return self._resize(img[y0:y0 + nh, x0:x0 + nw], cw, ch)
            # fallthrough: center crop
        if self.rand_crop and not self.rand_resize:
            x0 = self.rng.integers(0, max(w - cw, 0) + 1)
            y0 = self.rng.integers(0, max(h - ch, 0) + 1)
        else:
            x0 = max((w - cw) // 2, 0)
            y0 = max((h - ch) // 2, 0)
        out = img[y0:y0 + min(ch, h), x0:x0 + min(cw, w)]
        if out.shape[:2] != (ch, cw):
            out = self._resize(out, cw, ch)
        return out

    def __call__(self, img, cw, ch):
        if self.resize > 0:
            img = self._resize_short(img)
        img = self._crop(img, cw, ch)
        if self.rand_mirror and self.rng.random() < 0.5:
            img = img[:, ::-1]
        img = img.astype(np.float32)
        if self.mean is not None:
            img = img - self.mean
        if self.std is not None:
            img = img / self.std
        return img


def main():
    import cv2
    with open(sys.argv[1]) as f:
        cfg = json.load(f)
    c, ih, iw = cfg["data_shape"]
    label_width = int(cfg.get("label_width", 1))
    slot_imgs = int(cfg["slot_imgs"])
    n_slots = int(cfg["n_slots"])
    img_floats = c * ih * iw
    slot_floats = slot_imgs * (img_floats + label_width)
    shm = shared_memory.SharedMemory(name=cfg["shm_name"])
    buf = np.ndarray((n_slots * slot_floats,), dtype=np.float32,
                     buffer=shm.buf)
    rng = np.random.default_rng(int(cfg.get("seed", 0)))
    aug = Augmenter(cfg.get("aug", {}), rng)
    rec = open(cfg["rec_path"], "rb")

    out = sys.stdout

    def process(order):
        slot = int(order["slot"])
        base = slot * slot_floats
        imgs = buf[base:base + slot_imgs * img_floats].reshape(
            slot_imgs, c, ih, iw)
        labs = buf[base + slot_imgs * img_floats:
                   base + slot_floats].reshape(slot_imgs, label_width)
        try:
            for k, off in enumerate(order["items"]):
                label, body = _unpack(_read_record(rec, off))
                img = cv2.imdecode(np.frombuffer(body, np.uint8),
                                   cv2.IMREAD_COLOR)
                if img is None:
                    raise ValueError(f"undecodable image at offset {off}")
                img = img[:, :, ::-1]                 # BGR -> RGB
                img = aug(img, iw, ih)
                if img.ndim == 2:
                    img = img[:, :, None]
                imgs[k] = img.transpose(2, 0, 1)      # HWC -> CHW
                lab = np.atleast_1d(np.asarray(label, np.float32))
                labs[k, :] = 0.0
                labs[k, :min(label_width, lab.size)] = lab[:label_width]
            out.write(json.dumps({"slot": slot,
                                  "n": len(order["items"])}) + "\n")
        except Exception as e:                        # report, don't die
            out.write(json.dumps({"slot": slot, "error": str(e)}) + "\n")

    for line in sys.stdin:
        req = json.loads(line)
        if req.get("cmd") == "quit":
            break
        # chunked submission: one stdin line may carry several slot
        # orders (parent amortizes json+pipe overhead across batches);
        # replies stay one line per order, oldest first
        for order in req.get("orders") or (req,):
            process(order)
        out.flush()
    shm.close()
    rec.close()


if __name__ == "__main__":
    main()
