"""Process-wide compiled-program cache.

The reference amortizes graph-init cost per executor: once a
GraphExecutor is bound, its cached engine segments persist for the
executor's lifetime (reference: graph_executor.cc:333-446). Here the
analogous artifact is a jitted XLA program — and a *per-instance* cache
(the original ``Executor._jit_cache``) re-traces and re-compiles on
every rebind: train→eval module pairs, ``force_rebind``, ``reshape``,
and each BucketingModule bucket bound over a ``shared_group`` all paid
a full trace+compile for programs the process had already built.

This module is the process-wide home for those programs. Keys capture
everything that determines the traced computation:

  (symbol signature hash, bound arg/aux shapes+dtypes, ctx kind,
   mesh/topology token, layout flag, compute_dtype, remat segments)
  + (kind, kind-extras)

The mesh token (``parallel.mesh.mesh_token`` / ``SpmdPlan.cache_
token``) names the device topology — platform, axis layout, exact
device assignment, and (spmd) the param spec set. It exists because
compiled train programs bake their mesh's collective structure in
(psum shard counts, ZeRO reduce-scatter shapes): a mesh-shape change —
e.g. 1 → 8 host-platform devices in one process — must MISS, never
reuse a stale program (tests/test_program_cache.py pins the negative).

where ``kind`` is one of ``fwd_infer`` / ``fwd_train`` / ``fwd_bwd`` /
``fused_step`` / ``scan`` and the extras carry what only that kind
depends on (the watched-param set for gradient programs; the optimizer's
``fused_plan_token()``, the comm-plan token — replicated all-reduce vs
ZeRO-1 reduce-scatter, ``("comm", "ar"|"rs")`` — and the scan length K
for the fused/scan train steps; every gradient-bearing kind also
carries the remat-policy token ``("remat", none|dots|all)`` — a
checkpointed program and an unrematerialized one trace differently for
one symbol, mxnet_tpu/remat.py). Anything the key cannot capture — model-parallel plans, monitor
taps, the NaiveEngine debug mode — is simply not cached here and keeps
its per-executor lifecycle.

The cache is a bounded LRU (``MXNET_PROGRAM_CACHE_SIZE``, default 64
programs); eviction drops the jitted callable and with it XLA's
compiled executable. The ``executor.jit_cache.hit``/``.miss`` telemetry
counters account lookups (per-instance and process-wide hits count the
same — both mean "no new compile") and the
``executor.jit_cache.programs_live`` gauge tracks residency.

Serving additions (mxnet_tpu/serve): an inference server's bucket-
ladder programs are warmed once at startup and must then survive for
the process lifetime — a training rebind storm evicting a serving
program would reintroduce a compile into a latency SLO. ``pin(key)``
exempts an entry from LRU eviction (eviction skips pinned entries;
if every entry is pinned the cache grows past capacity rather than
break a pin); ``unpin(key)`` restores normal lifecycle. ``contains``/
``keys`` give warmup code residency introspection, and
``compile_count()`` is a monotone count of fresh program insertions —
the steady-state contract "zero compiles after warmup" is the delta
of this counter, independent of the telemetry switch.
"""
from __future__ import annotations

import hashlib
import math
import os
import re
import threading
from collections import OrderedDict

import numpy as np

from .telemetry import metrics as _metrics

__all__ = ["symbol_signature", "get", "put", "clear", "size",
           "attr_cache_stable", "pin", "unpin", "pinned", "contains",
           "keys", "compile_count"]

_ID_REPR = re.compile(r" at 0x[0-9a-fA-F]+")


def attr_cache_stable(value, _depth=0):
    """(stable?, reason) — is one op-attr value safe inside a cache key?

    ``symbol_signature`` hashes the symbol's JSON, so every attr value
    lands (stringified) in the program-cache key and the persistent XLA
    cache key. Stable means: the string is identical across processes
    and across re-constructions of the same logical graph, and the
    value compares equal to itself. Three ways to lose:

    * reprs embedding the object id (``<obj at 0x7f..>``) — a fresh key
      every construction: per-step retrace/recompile churn;
    * array attrs — numpy's repr truncates, so two *different* arrays
      can hash to ONE key: silent wrong-program reuse, worse than churn;
    * non-finite floats — NaN != NaN defeats every by-value cache
      downstream (the fused lr/wd device-array cache re-uploads per
      step).

    The retrace-churn analysis pass (analysis rule RC401) flags graph
    attrs through this predicate.
    """
    v = value
    if v is None or isinstance(v, (bool, str, bytes, int, np.integer)):
        return True, ""
    if isinstance(v, (float, np.floating)):
        if not math.isfinite(float(v)):
            return False, "non-finite float never compares equal"
        return True, ""
    if isinstance(v, (tuple, list)):
        if _depth > 4:
            return False, "deeply nested attr"
        for item in v:
            ok, why = attr_cache_stable(item, _depth + 1)
            if not ok:
                return False, why
        return True, ""
    if isinstance(v, (np.dtype, type)):
        return True, ""
    if isinstance(v, np.ndarray) or hasattr(v, "__array__"):
        return False, ("array repr truncates; distinct arrays can hash "
                       "to one cache key")
    rep = repr(v)
    if _ID_REPR.search(rep):
        return False, "repr embeds the object id"
    if callable(v):
        return False, "callable attrs do not serialize"
    return True, ""

_lock = threading.Lock()
_cache = OrderedDict()        # key tuple -> program callable
_pinned = set()               # keys exempt from LRU eviction (serving)
_compiles = 0                 # monotone count of fresh insertions


def _capacity():
    try:
        return max(1, int(os.environ.get("MXNET_PROGRAM_CACHE_SIZE", "64")))
    except ValueError:
        return 64


def _note_size_locked():
    _metrics.gauge("executor.jit_cache.programs_live").set(len(_cache))


def symbol_signature(symbol):
    """Stable structural hash of a Symbol graph (sha1 of its json).

    Memoized on the symbol object: the json walk is O(graph) and bind
    paths (bucketing, rebinding) hash the same symbol repeatedly.
    """
    sig = getattr(symbol, "_prog_cache_sig", None)
    if sig is None:
        sig = hashlib.sha1(symbol.tojson().encode("utf-8")).hexdigest()
        try:
            symbol._prog_cache_sig = sig
        except AttributeError:
            pass
    return sig


def get(key):
    """Cached program for ``key`` or None; refreshes LRU recency."""
    with _lock:
        fn = _cache.get(key)
        if fn is not None:
            _cache.move_to_end(key)
        return fn


def put(key, fn):
    """Insert a program, evicting least-recently-used beyond capacity.

    Pinned entries are never evicted: the scan walks oldest-first over
    unpinned keys only, so a fully-pinned cache overflows capacity
    instead of breaking a serving warmup's residency guarantee.
    """
    global _compiles
    cap = _capacity()
    with _lock:
        if key not in _cache:
            _compiles += 1      # a fresh trace/compile entered the cache
        _cache[key] = fn
        _cache.move_to_end(key)
        while len(_cache) > cap:
            victim = next((k for k in _cache
                           if k not in _pinned and k != key), None)
            if victim is None:      # everything else pinned: overflow
                break
            del _cache[victim]
        _note_size_locked()
    return fn


def pin(key):
    """Exempt ``key`` from LRU eviction (no-op if absent). Returns
    whether the key is resident — serving warmup asserts on it."""
    with _lock:
        if key in _cache:
            _pinned.add(key)
            return True
        return False


def unpin(key):
    """Restore normal LRU lifecycle for ``key``."""
    with _lock:
        _pinned.discard(key)


def pinned():
    """Snapshot of the pinned key set."""
    with _lock:
        return set(_pinned)


def contains(key):
    """Residency probe without touching LRU recency."""
    with _lock:
        return key in _cache


def keys():
    """Snapshot of resident keys, LRU-oldest first."""
    with _lock:
        return list(_cache)


def compile_count():
    """Monotone count of fresh program insertions (never reset by
    ``clear``): ``compile_count()`` deltas prove zero-compile steady
    state regardless of the telemetry enable switch."""
    with _lock:
        return _compiles


def clear():
    """Drop every cached program (tests; frees compiled executables).
    Pins are dropped with their entries."""
    with _lock:
        _cache.clear()
        _pinned.clear()
        _note_size_locked()


def size():
    with _lock:
        return len(_cache)
