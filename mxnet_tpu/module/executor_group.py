"""DataParallelExecutorGroup: device-parallel execution of one symbol.

Reference design (reference: python/mxnet/module/executor_group.py, 651 LoC):
slice the batch across devices (``decide_slices``, :207-231), bind one
Executor per context (:537-629), fan out forward/backward, sum gradients via
KVStore.

TPU-native design — the central SPMD decision of this framework: bind ONE
executor whose data arrays are sharded over a first-class named
``jax.sharding.Mesh`` (``parallel/mesh.build_mesh``) and whose params are
placed per a sharding plan. XLA's SPMD partitioner then runs the very
same jitted fwd+bwd program on every chip and inserts the gradient
all-reduce (psum over ICI) automatically — replacing the reference's
per-device executors + KVStore push/pull with compiler-inserted
collectives (SURVEY.md §5.8 "TPU-native equivalent"). Two arrangements:

* default — 1-D ``data`` mesh over the bound contexts, params
  replicated (the shape every kvstore-era test pins);
* ``spmd=True`` (``Module.bind/fit(spmd=True)`` / ``MXNET_SPMD``) — the
  multi-axis mesh from ``MeshConfig``/``MXNET_MESH_*`` with a
  ``parallel/spmd.SpmdPlan``: params sharded per ``placement.py``'s
  ctx_group lowering on the ``model`` axis, optimizer state riding the
  same specs, ZeRO-1 as a spec change on the state leaves, kvstore
  optional.

The class keeps the reference's surface (param_arrays/grad_arrays/
forward/backward/update_metric) so Module and the KVStore update paths
work unchanged: with one logical executor, ``param_arrays`` holds one
entry per param.
"""
from __future__ import annotations

import collections
import logging
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray import NDArray, zeros as nd_zeros
from ..io import DataDesc
from .. import program_cache as _progcache
from .. import telemetry as _telemetry
from ..parallel import mesh as _mesh_mod
from ..parallel import zero as _zero_mod
from ..parallel.spmd import SpmdPlan

__all__ = ["DataParallelExecutorGroup"]


def _ssq32(vals):
    """Traced global sum of squares over an iterable of arrays (f32
    accumulator). Shared by the per-step health stats and the
    window-boundary param-stat readings."""
    acc = jnp.zeros((), jnp.float32)
    for v in vals:
        v32 = v.astype(jnp.float32)
        acc = acc + jnp.sum(v32 * v32)
    return acc


def _window_param_stats(health, w_start, w_end, watched):
    """Add the window-level param stats to a health dict (traced).

    param-norm and update-ratio need a full pass over the param set;
    done per step that pass reads the donated/carried buffers and
    defeats XLA's in-place update (measured: an O(params) copy every
    step). Both are therefore computed ONCE per dispatch window — over
    the window's closing params and the window-wide delta — where the
    single amortised read is in the noise. On the K=1 path a window IS
    one step, so the reference per-step semantics are unchanged there;
    on the scan path update_ratio reports the K-step window ratio.
    """
    wsq = _ssq32(w_end[nm] for nm in watched)
    dsq = _ssq32(w_end[nm] - w_start[nm].astype(w_end[nm].dtype)
                 for nm in watched)
    pn = jnp.sqrt(wsq)
    out = dict(health)
    out["param_norm"] = pn
    out["update_ratio"] = jnp.sqrt(dsq) / jnp.maximum(
        pn, jnp.float32(1e-12))
    return out


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req="write", state_names=None, compute_dtype=None,
                 spmd=False, mesh_config=None):
        self.symbol = symbol
        self.compute_dtype = compute_dtype
        self.contexts = contexts
        self.workload = workload
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.logger = logger
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []
        self.param_names = param_names
        self._zero_plan = None          # set by setup_fused_step
        self._state_layout = None       # flat-shard state transport

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        data_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                       for x in data_shapes]
        if label_shapes is not None:
            label_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                            for x in label_shapes]
        self.data_names = [x.name for x in data_shapes]
        self.label_names = [x.name for x in label_shapes] \
            if label_shapes is not None else []

        # grad_req per arg (reference: executor_group.py:233-268)
        if isinstance(grad_req, str):
            base_req = grad_req
        else:
            base_req = None
        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                req = (base_req or (grad_req.get(name, "null")
                                    if isinstance(grad_req, dict) else "write"))
                if not for_training or name in self.fixed_param_names:
                    req = "null"
            elif name in self.data_names:
                req = (base_req or "write") if inputs_need_grad else "null"
                if not for_training:
                    req = "null"
            else:
                req = "null"
            self.grad_req[name] = req

        # ---- mesh construction over the bound contexts -------------------
        # both arrangements go through parallel/mesh.build_mesh — ONE
        # first-class named mesh per binding (the 1-D ad-hoc Mesh this
        # class used to build inline is the degenerate data-only case)
        devices = [c.jax_device() for c in contexts]
        self._n_dev = len(devices)
        if self._n_dev > 1 and len(set(devices)) != self._n_dev:
            raise MXNetError(
                f"contexts {contexts} resolve to only {len(set(devices))} "
                f"distinct devices ({sorted(set(str(d) for d in devices))}). "
                "On a CPU host set XLA_FLAGS="
                "--xla_force_host_platform_device_count=N to get N virtual "
                "devices.")
        self._spmd_plan = None
        if spmd:
            # param specs are derived at bind time (shapes needed);
            # zero is enabled at optimizer-arming time
            self._spmd_plan = SpmdPlan(
                SpmdPlan.build_mesh_for(devices, mesh_config))
            self._mesh = self._spmd_plan.mesh
            self._data_sharding = self._spmd_plan.data_sharding()
            self._repl_sharding = self._spmd_plan.replicated
            self._stacked_sharding = self._spmd_plan.data_sharding(
                stacked=True)
            self._n_data = self._spmd_plan.n_data_shards()
        elif self._n_dev > 1:
            self._mesh = _mesh_mod.build_mesh(devices=devices)
            self._data_sharding = NamedSharding(self._mesh, P("data"))
            self._repl_sharding = NamedSharding(self._mesh, P())
            # K-stacked batches: axis 0 is the scan step, batch is axis 1
            self._stacked_sharding = NamedSharding(self._mesh,
                                                   P(None, "data"))
            self._n_data = self._n_dev
        else:
            self._mesh = None
            self._data_sharding = None
            self._repl_sharding = None
            self._stacked_sharding = None
            self._n_data = 1

        self.batch_size = data_shapes[0].shape[
            DataDesc.get_batch_axis(data_shapes[0].layout)]
        if self._n_data > 1 and self.batch_size % self._n_data != 0:
            raise MXNetError(
                f"batch size {self.batch_size} must be divisible by the "
                f"data-axis size {self._n_data}")

        self.data_shapes = data_shapes
        self.label_shapes = label_shapes

        self._bind_exec(shared_group)

    # ------------------------------------------------------------------ bind
    def _place(self, arr, kind, name=None):
        """Device-place a jnp array: batch-sharded, per-plan param
        sharding (SPMD mode), or replicated."""
        if self._mesh is None:
            return jax.device_put(arr, self.contexts[0].jax_device())
        if kind == "data":
            if self._spmd_plan is not None:
                # shape-aware spec: P(data, seq) on (batch, sequence)
                # when the plan carries a nonempty seq axis (the
                # long-context layout ring attention consumes)
                sharding = self._spmd_plan.data_sharding_for(arr.shape)
            else:
                sharding = self._data_sharding
        elif self._spmd_plan is not None and name is not None:
            sharding = self._spmd_plan.param_sharding(name)
        else:
            sharding = self._repl_sharding
        return jax.device_put(arr, sharding)

    def _bind_exec(self, shared_group):
        from ..executor import Executor
        shapes = {d.name: d.shape for d in self.data_shapes}
        if self.label_shapes is not None:
            shapes.update({l.name: l.shape for l in self.label_shapes})
        arg_shapes, out_shapes, aux_shapes = \
            self.symbol.infer_shape(**shapes)
        arg_types = {d.name: d.dtype for d in self.data_shapes}
        # params declared with an explicit var dtype bind a cell of that
        # dtype (the int8 tier's quantized weights — set_params would
        # otherwise silently upcast them into a float32 cell, wasting
        # the HBM the quantization bought); analysis rule GV105 audits
        # the same declaration
        for n in self.symbol._topo_nodes():
            if n.is_variable and n._extra.get("__dtype__") and \
                    n.name not in arg_types:
                arg_types[n.name] = np.dtype(n._extra["__dtype__"])

        if self._spmd_plan is not None:
            # lower ctx_group tags onto the model axis now that shapes
            # are known (re-derived on reshape: divisibility may change)
            self._spmd_plan.derive_param_specs(
                self.symbol, dict(zip(self.arg_names, arg_shapes)))

        shared_params = {}
        if shared_group is not None:
            shared_params = dict(zip(shared_group.arg_names,
                                     shared_group.executor.arg_arrays))

        args = {}
        grads = {}
        # cells reused from a shared_group: the donation/aliasing
        # analysis pass (analysis rule DA202) flags these if a fused
        # (donating) plan ever arms over them
        self._shared_param_names = set()
        for name, shape in zip(self.arg_names, arg_shapes):
            kind = "data" if (name in self.data_names or
                              name in self.label_names) else "param"
            if name in shared_params and kind == "param":
                args[name] = shared_params[name]  # shared NDArray cell
                self._shared_param_names.add(name)
            else:
                dtype = arg_types.get(name, np.float32)
                args[name] = NDArray(self._place(
                    jnp.zeros(shape, dtype=np.dtype(dtype)
                              if dtype != np.float64 else np.float32),
                    kind, name))
            if self.grad_req.get(name, "null") != "null":
                grads[name] = NDArray(self._place(
                    jnp.zeros(shape, dtype=np.float32), kind, name))
        aux = {}
        shared_aux = {}
        if shared_group is not None:
            shared_aux = dict(zip(shared_group.aux_names,
                                  shared_group.executor.aux_arrays))
        # aux cells honor a declared dtype (attention_decode's int32
        # cache cursor; the KV-cache arrays stay f32 master width)
        aux_types = {n.name: np.dtype(n._extra["__dtype__"])
                     for n in self.symbol._topo_nodes()
                     if n.is_variable and n._extra.get("__is_aux__")
                     and n._extra.get("__dtype__")}
        for name, shape in zip(self.aux_names, aux_shapes):
            want = np.dtype(aux_types.get(name, np.float32))
            cell = shared_aux.get(name)
            # share an aux cell only when shape AND dtype agree: a
            # slot-pooled decode ladder binds the SAME aux names at a
            # different slot count per rung (the KV cache pool scales
            # with the bucket key) — aliasing the leader's cell there
            # would hand every rung a wrongly-shaped cache
            if cell is not None and tuple(cell.shape) == tuple(shape) \
                    and np.dtype(str(cell.asjax().dtype)) == want:
                aux[name] = cell
            else:
                aux[name] = NDArray(
                    self._place(jnp.zeros(shape, dtype=want),
                                "param", name))

        # device-topology token for the program-cache keys: a compiled
        # program bakes its mesh's collective structure in, so a mesh
        # change (1→8 devices, axis reshape, different spec set) must
        # never reuse a stale program
        if self._spmd_plan is not None:
            mesh_token = self._spmd_plan.cache_token()
        elif self._mesh is not None:
            mesh_token = _mesh_mod.mesh_token(self._mesh)
        else:
            mesh_token = None           # Executor derives a device token
        self.executor = Executor(self.symbol, self.contexts[0], args, grads,
                                 self.grad_req, aux,
                                 compute_dtype=self.compute_dtype,
                                 mesh_token=mesh_token,
                                 spmd_plan=self._spmd_plan)
        self.execs = [self.executor]  # reference-compat alias

        # flat layout — one logical sharded executor, so one array per
        # param (the reference's per-device inner lists don't exist here);
        # grad entry is None for fixed/untrained params, keeping 1:1 zip
        self.param_arrays = [self.executor.arg_dict[name]
                             for name in self.param_names]
        self.grad_arrays = [self.executor.grad_dict.get(name)
                            for name in self.param_names]
        self.aux_arrays = list(self.executor.aux_arrays)

        self.data_arrays = [self.executor.arg_dict[name]
                            for name in self.data_names]
        self.label_arrays = [self.executor.arg_dict[name]
                             for name in self.label_names
                             if name in self.executor.arg_dict]

    # ------------------------------------------------------- fused training
    def setup_fused_step(self, optimizer, zero_stage=0, remat=None):
        """Compile forward+backward+optimizer-update into ONE jitted XLA
        program (the TPU-native analog of the reference's bulk train
        segment, graph_executor.cc:678-756, plus its fused update ops).

        ``zero_stage=1`` selects the in-program reduce-scatter comm plan
        (parallel/zero.py) on a multi-device mesh: gradients arrive
        shard-wise, the update runs on 1/N flat shards with sharded
        optimizer state, and the new params all-gather back — otherwise
        the replicated (all-reduce) plan runs unchanged.

        ``remat`` (default ``MXNET_REMAT_POLICY``, else ``none``)
        applies a rematerialization policy to the step's differentiated
        forward — ``dots`` keeps matmul/conv outputs saved and
        recomputes the elementwise chains between them, ``all`` replays
        the whole forward inside the backward — and additionally
        donates the step's eval-only intermediates (the rng key chain
        and, when the training forward refreshes every aux entry, the
        aux buffers). The policy is part of the program-cache key and
        of the kernel-tier autotune key (mxnet_tpu/remat.py).

        Per-batch work then becomes: slice batch -> async device_put ->
        one XLA dispatch -> buffer swaps. Returns False when the
        optimizer or binding can't express it (imperative path remains).
        """
        from ..executor import naive_engine_active
        from .. import remat as _remat
        self._zero_plan = None
        self._state_layout = None
        self._remat_policy = _remat.resolve(remat)
        plan = optimizer.fused_plan()
        if plan is None or not self.for_training or self.inputs_need_grad:
            return False
        if naive_engine_active():
            # NaiveEngine debug mode: keep the imperative per-phase path so
            # every op replays serially through the un-jitted runner
            return False
        if any(self.grad_req.get(nm) not in ("write", "null")
               for nm in self.arg_names):
            return False
        init_state, update = plan
        exe = self.executor
        watched = [nm for nm in self.param_names
                   if self.grad_req.get(nm) == "write"]
        if not watched:
            return False

        # comm plan: in-program reduce-scatter + sharded update (ZeRO-1)
        # needs a data mesh and an elementwise update; anything else
        # keeps the replicated all-reduce plan. Under the SPMD plan,
        # ZeRO-1 is purely a spec change: state_spec flips to P('data')
        # over the flat layout and the step applies it via
        # zero.apply_spec_update — no plan object threaded through.
        spmd_plan = self._spmd_plan
        can_shard = (self._mesh is not None and
                     (spmd_plan.can_zero() if spmd_plan is not None
                      else self._n_data > 1))
        if (zero_stage and can_shard
                and getattr(optimizer, "fused_update_elementwise", False)):
            if spmd_plan is not None:
                spmd_plan.enable_zero()
                self._state_layout = spmd_plan.state_layout
            else:
                from ..parallel.zero import ZeroPlan
                self._zero_plan = ZeroPlan(self._mesh, "data")
                self._state_layout = self._zero_plan
        elif zero_stage:
            self.logger.info(
                "zero_stage=%s requested but unavailable (data shards=%s, "
                "elementwise=%s); using the replicated update plan",
                zero_stage, self._n_data,
                getattr(optimizer, "fused_update_elementwise", False))
        zero_plan = self._zero_plan

        runner = exe._runner
        loss_mask = exe._loss_mask
        # (output index, label name) pairs, positional like
        # Accuracy.update's zip(labels, preds) — names missing from the
        # executor keep their index so pairings never shift
        metric_pairs = [(i, nm) for i, nm in enumerate(self.label_names)
                        if nm in exe.arg_dict]
        self._fused_metric_pairs = metric_pairs

        # Gradients as program OUTPUTS cost ~5% of the step (measured on
        # v5e: 161 extra materializations the fuser must keep live past
        # the update instead of folding into it). The default fit loop
        # never reads them, so they're off unless requested; the staged
        # (non-fused) path always populates grad_dict.
        keep_grads = os.environ.get("MXNET_FUSED_KEEP_GRADS", "0") == "1"
        if not keep_grads:
            # the fused program will never write these buffers — poison
            # them once so a stale read returns NaN loudly instead of
            # plausible pre-step values (set MXNET_FUSED_KEEP_GRADS=1 for
            # live gradients, or install a monitor for the staged path)
            gd = exe.grad_dict
            for nm in watched:
                dst = gd.get(nm)
                if dst is not None and \
                        np.issubdtype(dst.dtype, np.floating):
                    dst._set(jnp.full(dst.shape, jnp.nan,
                                      dst.asjax().dtype))

        remat_policy = self._remat_policy

        # training-health plane (telemetry/health.py): when armed, the
        # program computes a small fixed stat set INSIDE the jitted
        # step — per-step grad global L2 norm, per-loss-head loss and
        # non-finite flag (returned as extra stacked ys), plus one
        # window-level param-norm / update-ratio reading (see
        # _window_param_stats) — all read by the host at window
        # boundaries where it already syncs. Read-only over values the
        # step computes anyway, so armed training is bit-identical to
        # unarmed; arming keys the program cache below.
        health_armed = _telemetry.health.armed()

        # lr/wd arrive as TWO stacked f32 arrays, not 2x161 python
        # scalars: scalar jit args each become their own host->device
        # transfer per dispatch, which through a remote chip is hundreds
        # of tiny RPCs per step
        def step(w, rest, aux_vals, key, states, lr_arr, wd_arr):
            # rng chain lives ON DEVICE: split here (traced) and return
            # the successor key, so per-step randomness costs zero extra
            # host round-trips (next_key() per step was a device dispatch
            # + transfer through the remote-chip tunnel)
            key, rng = jax.random.split(key)

            def f(wv):
                return runner({**rest, **wv}, aux_vals, True, rng)

            # remat policy: shrink the saved-residual set of this vjp
            # (identity under "none" — the traced program is unchanged)
            f = _remat.wrap(f, remat_policy)

            outs, vjp_fn, new_aux = jax.vjp(f, w, has_aux=True)
            heads = [jnp.ones(o.shape, o.dtype) if is_loss
                     else jnp.zeros(o.shape, o.dtype)
                     for o, is_loss in zip(outs, loss_mask)]
            (grads,) = vjp_fn(heads)
            new_w, new_states = {}, {}
            for i, nm in enumerate(watched):
                g = grads[nm].astype(w[nm].dtype)
                if spmd_plan is not None:
                    # spec-driven: the plan's PartitionSpecs pin the
                    # gradient (the psum/reduce-scatter XLA emits), the
                    # update layout, and the new weights (donation needs
                    # input sharding == output sharding)
                    if spmd_plan.zero:
                        nw, ns = _zero_mod.apply_spec_update(
                            update, w[nm], g, states[nm],
                            lr_arr[i], wd_arr[i], spmd_plan.mesh,
                            spmd_plan.state_spec(nm),
                            out_spec=spmd_plan.param_spec(nm))
                    else:
                        p_sh = spmd_plan.param_sharding(nm)
                        g = jax.lax.with_sharding_constraint(g, p_sh)
                        nw, ns = update(w[nm], g, states[nm],
                                        lr_arr[i], wd_arr[i])
                        nw = jax.lax.with_sharding_constraint(nw, p_sh)
                        ns = jax.tree.map(
                            lambda x: jax.lax.with_sharding_constraint(
                                x, p_sh) if x.shape == nw.shape else x,
                            ns)
                elif zero_plan is None:
                    nw, ns = update(w[nm], g, states[nm],
                                    lr_arr[i], wd_arr[i])
                else:
                    nw, ns = zero_plan.apply(update, w[nm], g,
                                             states[nm],
                                             lr_arr[i], wd_arr[i])
                new_w[nm] = nw
                new_states[nm] = ns
            # top-1 correct counts per (label, output) pair, computed
            # inside the program: the Accuracy metric then costs zero
            # extra dispatches per batch (its own device-side argmax
            # was one more round trip through a remote-chip tunnel)
            mets = []
            for i, nm in metric_pairs:
                if i >= len(outs):
                    break
                o, lab = outs[i], rest[nm]
                if o.ndim > 1 and o.shape != lab.shape:
                    # classification semantics only: prediction classes
                    # must align 1:1 with label elements after argmax
                    # (detection-style structured labels skip the
                    # in-step count and take the general metric path)
                    if int(np.prod(o.shape[:-1])) != lab.size:
                        break
                    p = jnp.argmax(o, axis=-1)
                elif o.shape == lab.shape:
                    p = o
                else:
                    break
                l = lab.astype(jnp.int32).ravel()
                mets.append(jnp.sum(p.astype(jnp.int32).ravel() == l))
            health = None
            if health_armed:
                f32 = jnp.float32
                # per-step stats ONLY cover values this step already
                # materialises (grads, outputs): reductions over the
                # param set are NOT free here — params ride the donated
                # scan carry, and any extra reader defeats the in-place
                # update (measured: an O(params) copy per step, +15% on
                # a 1M-param epoch). param-norm / update-ratio are
                # computed once per dispatch window by the program
                # wrappers below instead.
                gsq = _ssq32(grads[nm] for nm in watched)
                # per-loss-head loss value: cross-entropy against the
                # paired label for classification heads, squared error
                # for same-shape heads, mean output for heads that ARE
                # the loss (MakeLoss-style) — mirrors the mets pairing
                label_for = dict((i, nm) for i, nm in metric_pairs)
                losses = []
                for i, (o, is_loss) in enumerate(zip(outs, loss_mask)):
                    if not is_loss:
                        continue
                    nm = label_for.get(i)
                    lab = rest.get(nm) if nm is not None else None
                    o32 = o.astype(f32)
                    if lab is not None and o.ndim > 1 and \
                            o.shape != lab.shape and \
                            int(np.prod(o.shape[:-1])) == lab.size:
                        p = o32.reshape((-1, o.shape[-1]))
                        idx = lab.astype(jnp.int32).reshape((-1, 1))
                        picked = jnp.take_along_axis(p, idx, axis=1)
                        losses.append(-jnp.mean(jnp.log(
                            jnp.maximum(picked, 1e-30))))
                    elif lab is not None and o.shape == lab.shape:
                        d = o32 - lab.astype(f32)
                        losses.append(jnp.mean(d * d))
                    else:
                        losses.append(jnp.mean(o32))
                loss_vec = jnp.stack(losses) if losses \
                    else jnp.zeros((0,), f32)
                finite = (jnp.isfinite(gsq)
                          & jnp.all(jnp.isfinite(loss_vec)))
                # raw scalars, NOT packed into one vector: a pack op
                # (stack/concatenate) is measurably slower in-program
                # than returning the scalars as-is on micro-steps
                health = {
                    "grad_norm": jnp.sqrt(gsq),
                    "loss": loss_vec,
                    "nonfinite": 1.0 - finite.astype(f32),
                }
            return (outs, new_aux, new_w, new_states,
                    grads if keep_grads else None, key, mets, health)

        # donate the watched params and optimizer states: both are
        # replaced by same-shaped outputs every step, so XLA updates them
        # in place instead of allocating fresh buffers. They get their own
        # arguments precisely so donation is safe — `rest` still carries
        # data/label entries that _load_batch can alias to iterator
        # arrays, and donating those would delete the caller's buffers
        # out from under it (measured: "Array has been deleted" in eval
        # paths sharing those arrays). Aux (BN stats) stays undonated by
        # default for the same reason: eval paths read the same cells
        # mid-epoch. A remat policy extends the donation set to the
        # step's eval-only intermediates — the rng key chain, and the
        # aux buffers when the training forward provably refreshes EVERY
        # aux entry (cells then re-point at the returned buffers before
        # any reader runs; an aux entry the step passes through untouched
        # would leave a deleted buffer behind, so partial coverage keeps
        # aux undonated).
        donate = (0, 4)
        if remat_policy != "none":
            donate = (0, 3, 4)
            if self._aux_fully_refreshed():
                donate = (0, 2, 3, 4)
        self._fused_donate = donate
        self._step_core = step      # pure; the scan program re-uses it
        self._fused_keep_grads = keep_grads
        # the comm-plan token keys the traced collective structure:
        # replicated all-reduce vs reduce-scatter/shard-update/all-gather
        # trace differently even for identical symbols and optimizers;
        # the remat token keys the checkpoint-policy + donation shape
        zero_armed = zero_plan is not None or \
            (spmd_plan is not None and spmd_plan.zero)
        self._fused_cache_key = exe.program_cache_key(
            "fused_step", tuple(watched), tuple(metric_pairs), keep_grads,
            optimizer.fused_plan_token(),
            ("comm", "rs" if zero_armed else "ar"),
            ("remat", remat_policy),
            ("health", health_armed))
        self._fused_prog = None
        if self._fused_cache_key is not None:
            self._fused_prog = _progcache.get(self._fused_cache_key)
        if health_armed:
            # single-step program: every step is its own dispatch
            # window, so the window-level param stats land here too
            def fused_one(w, rest, aux_vals, key, states, lr_arr,
                          wd_arr):
                (outs, new_aux, new_w, new_states, grads, key, mets,
                 health) = step(w, rest, aux_vals, key, states,
                                lr_arr, wd_arr)
                health = _window_param_stats(health, w, new_w, watched)
                return (outs, new_aux, new_w, new_states, grads, key,
                        mets, health)
            prog_fn = fused_one
        else:
            prog_fn = step
        if self._fused_prog is not None:
            if _telemetry.enabled():
                _telemetry.counter("executor.jit_cache.hit").inc()
        else:
            if _telemetry.enabled():
                _telemetry.counter("executor.jit_cache.miss").inc()
            self._fused_prog = _telemetry.wrap_dispatch(
                jax.jit(prog_fn, donate_argnums=donate), "fused_step")
            if self._fused_cache_key is not None:
                _progcache.put(self._fused_cache_key, self._fused_prog)
        self._scan_prog = None      # K-step lax.scan program (lazy)
        self._scan_K = 0
        self._scan_failed = False
        self._scan_results = collections.deque()
        self._scan_lrwd = (None, None, None)
        self._fused_watched = watched
        from .. import random as _random
        self._fused_key = _random.next_key()   # device-chained thereafter
        self._fused_rng_gen = _random.generation()
        self._fused_lrwd = (None, None, None)  # (key, lr_arr, wd_arr)
        self._fused_metric_scalars = None
        self._last_health = None    # just-dispatched device stat vector
        self._health_queue = collections.deque()   # awaiting readiness
        self._health_armed = health_armed      # drained by take_health()
        # the watched cells must own their buffers exclusively before the
        # first donated step: init_params aliases the same arrays into
        # Module._arg_params, and donating a shared buffer would delete it
        # out from under that holder
        ad = exe.arg_dict
        for nm in watched:
            ad[nm]._set(jnp.array(ad[nm].asjax(), copy=True))
        self._fused_states = {}
        for nm in watched:
            w = exe.arg_dict[nm].asjax()
            if self._state_layout is not None:
                # ZeRO-1 (either plan): created directly in the
                # (n, chunk) sharded layout — each device holds only
                # its 1/N state slice
                self._fused_states[nm] = self._state_layout.init_state(
                    init_state, w)
            else:
                # param-shaped state rides the param's own sharding
                # (replicated, or the SPMD plan's model-axis spec);
                # differently-shaped leaves replicate
                def _put(x, _w=w):
                    if self._mesh is None or \
                            getattr(x, "shape", ()) == _w.shape:
                        return jax.device_put(x, _w.sharding)
                    return jax.device_put(x, self._repl_sharding)
                self._fused_states[nm] = jax.tree.map(_put, init_state(w))
        return True

    def _aux_fully_refreshed(self):
        """Does one training forward return a new value for EVERY aux
        entry? (True for the BatchNorm moving-stat contract — and the
        empty-aux case.) Gates aux donation under a remat policy: a
        pass-through aux entry would otherwise be left as a deleted
        buffer in its cell. Pure trace (``jax.eval_shape``)."""
        import jax as _jax
        exe = self.executor
        if not exe.aux_names:
            return True
        try:
            _outs, new_aux = _jax.eval_shape(
                lambda a, x, r: exe._runner(a, x, True, r),
                exe._arg_vals(), exe._aux_vals(),
                _jax.random.PRNGKey(0))
            return set(new_aux) == set(exe.aux_names)
        except Exception:
            return False

    def fused_memory_report(self):
        """Byte accounting of the armed fused step under the active
        remat policy: the VJP residual set (the activations stored
        between the forward and backward halves — what a remat policy
        shrinks), plus the param/batch footprints for headroom math.
        Trace-only (``remat.residual_bytes``); returns None when the
        fused step is not armed. Mirrored into ``memory.fused.*`` gauges
        for diagnose/bench consumption."""
        import jax as _jax
        from .. import remat as _remat
        if getattr(self, "_step_core", None) is None:
            return None
        exe = self.executor

        def nbytes(tree):
            return int(sum(
                int(np.prod(v.shape)) * v.dtype.itemsize
                for v in _jax.tree_util.tree_leaves(tree)))

        arg_vals = exe._arg_vals()
        w = {nm: arg_vals.pop(nm) for nm in self._fused_watched}
        aux_vals = exe._aux_vals()
        rng = _jax.random.PRNGKey(0)
        runner = exe._runner

        def f(wv):
            return runner({**arg_vals, **wv}, aux_vals, True, rng)

        policy = getattr(self, "_remat_policy", "none")
        try:
            resid = _remat.residual_bytes(_remat.wrap(f, policy), w)
        except Exception:
            return None
        batch_names = set(self.data_names) | set(self.label_names)
        report = {
            "policy": policy,
            "residual_bytes": resid,
            "param_bytes": nbytes(w),
            "state_bytes": nbytes(self._fused_states),
            "batch_bytes": nbytes([v for nm, v in arg_vals.items()
                                   if nm in batch_names]),
            "batch_size": self.batch_size,
            "donated_args": list(getattr(self, "_fused_donate", (0, 4))),
        }
        for k in ("residual_bytes", "param_bytes", "state_bytes",
                  "batch_bytes"):
            _telemetry.gauge(f"memory.fused.{k}",
                             policy=policy).set(report[k])
        _telemetry.flightrec.note("memory.fused_step", **{
            k: report[k] for k in ("policy", "residual_bytes",
                                   "param_bytes", "batch_bytes")})
        return report

    def static_memory_plan(self, policy=None, buckets=None,
                           capacity_bytes=None):
        """Static peak-HBM plan for this binding — the zero-trace fast
        path of the batch-bucket headroom gate.

        Same component semantics as ``fused_memory_report`` (the tests
        cross-check the two within 5%) but computed purely from the
        graph by ``analysis.memplan``: no ``eval_shape``, no trace, no
        armed optimizer required. When the fused step IS armed, the
        exact state-tree bytes and remat policy are used; otherwise the
        planner's optimizer-multiplier estimate. Returns the plan dict
        (plus ``headroom_bucket`` when ``buckets``+``capacity_bytes``
        are given), mirrored into the ``memplan.*`` gauges.
        """
        from .. import remat as _remat
        from ..analysis import memplan as _memplan
        shapes = {d.name: tuple(d.shape) for d in self.data_shapes}
        for l in (self.label_shapes or []):
            shapes[l.name] = tuple(l.shape)
        state_bytes = None
        states = getattr(self, "_fused_states", None)
        if states:
            # exact armed-state bytes (the flat ZeRO tree is the full
            # (n, chunk) layout — global, like the estimate; the
            # planner divides per device when zero=True)
            state_bytes = int(sum(
                int(np.prod(v.shape)) * v.dtype.itemsize
                for v in jax.tree_util.tree_leaves(states)))
        policy = policy or getattr(self, "_remat_policy", None) \
            or _remat.active()
        plan = _memplan.plan_symbol(
            self.symbol, shapes, policy=policy,
            for_training=self.for_training,
            compute_dtype=self.compute_dtype,
            n_data=self._n_data, spmd_plan=self._spmd_plan,
            zero=bool(self._state_layout is not None
                      or (self._spmd_plan is not None
                          and self._spmd_plan.zero)),
            donation=getattr(self, "_fused_prog", None) is not None,
            fixed_params=self.fixed_param_names,
            state_bytes=state_bytes)
        _memplan.record_plan(plan)
        if buckets and capacity_bytes and plan.get("per_sample_bytes"):
            from ..telemetry.memory import batch_headroom
            plan["headroom_bucket"] = batch_headroom(
                capacity_bytes, plan["fixed_bytes"] + plan["grad_bytes"],
                plan["per_sample_bytes"], buckets)
        return plan

    # ----------------------------------------------- fused-state transport
    def export_fused_states(self):
        """Host-format (param-shaped numpy) fused optimizer states — the
        checkpoint representation, identical for the replicated and the
        ZeRO-sharded layouts (either plan) so checkpoints move between
        arrangements."""
        if self._state_layout is None:
            return jax.tree.map(np.asarray, self._fused_states)
        return {nm: self._state_layout.export_state(
                    st, self.executor.arg_dict[nm].shape)
                for nm, st in self._fused_states.items()}

    def import_fused_states(self, states_host):
        """Load host-format states back into the armed plan's layout."""
        if self._state_layout is None:
            self._fused_states = jax.tree.map(
                lambda old, new: jax.device_put(np.asarray(new),
                                                old.sharding),
                self._fused_states, states_host)
            return
        self._fused_states = {
            nm: (self._state_layout.import_state(states_host[nm])
                 if nm in states_host else st)
            for nm, st in self._fused_states.items()}

    def import_staged_state(self, nm, staged):
        """Project one param's staged (param-shaped, possibly nested)
        optimizer state onto the fused device layout."""
        layout = self._state_layout

        def walk(old, new):
            if isinstance(old, (tuple, list)):
                return type(old)(walk(o, n) for o, n in zip(old, new))
            arr = new.asnumpy() if isinstance(new, NDArray) \
                else np.asarray(new)
            if layout is not None:
                return jax.device_put(layout._flat(jnp.asarray(arr)),
                                      layout.sharded)
            return jax.device_put(arr, old.sharding)

        self._fused_states[nm] = walk(self._fused_states[nm], staged)

    def defused_states(self):
        """Device-side fused states in param shape, for migrating into
        the staged updater (Module._defuse)."""
        if self._state_layout is None:
            return dict(self._fused_states)
        return {nm: self._state_layout.device_state_to_param_shape(
                    st, self.executor.arg_dict[nm].shape)
                for nm, st in self._fused_states.items()}

    # ------------------------------------------------------- rng transport
    def rng_chain(self):
        """Host copy of the device-chained rng key (None when the fused
        path never armed). Part of the exact-resume state: the dropout
        stream of step N+1 is a pure function of this key."""
        key = getattr(self, "_fused_key", None)
        return None if key is None else np.asarray(key)

    def set_rng_chain(self, key):
        """Reinstate a checkpointed device rng chain and re-tag the
        generation so the restored chain is not immediately re-drawn."""
        from .. import random as _random
        self._fused_key = jnp.asarray(np.asarray(key))
        self._fused_rng_gen = _random.generation()

    def fused_step(self, data_batch, lrs, wds):
        """Run one fused train step; swap new params/state/outputs in
        (gradients are emitted and written back only under
        ``MXNET_FUSED_KEEP_GRADS=1`` — they cost ~5% of the step).

        Step attribution (telemetry/stepattr.py, armed fit loops only):
        host batch staging counts as ``assemble``, the async program
        call as ``dispatch``, and — every single step being its own
        window boundary — a block-until-ready on the advanced params as
        ``device``."""
        from .. import random as _random
        _sa = _telemetry.stepattr
        sa_on = _sa.active()
        if sa_on:
            sa_t0 = _sa.clock()
        exe = self.executor
        self._load_batch(data_batch)
        if self._fused_rng_gen != _random.generation():
            # mx.random.seed() was called since the last step: re-draw
            # the device chain from the reseeded host chain so seeding
            # stays effective mid-training (reference seed semantics)
            self._fused_key = _random.next_key()
            self._fused_rng_gen = _random.generation()

        arg_vals = exe._arg_vals()
        w = {nm: arg_vals.pop(nm) for nm in self._fused_watched}
        # lr/wd device arrays are cached by value: with a fixed schedule
        # this is zero host->device transfers per step (two per step
        # otherwise — each a round trip through the remote-chip tunnel)
        lrwd_key = (tuple(lrs[nm] for nm in self._fused_watched),
                    tuple(wds[nm] for nm in self._fused_watched))
        if self._fused_lrwd[0] != lrwd_key:
            self._fused_lrwd = (
                lrwd_key, jnp.asarray(lrwd_key[0], jnp.float32),
                jnp.asarray(lrwd_key[1], jnp.float32))
        _, lr_arr, wd_arr = self._fused_lrwd
        if sa_on:
            sa_t1 = _sa.clock()
            _sa.note("assemble", sa_t1 - sa_t0)
        (outs, new_aux, new_w, new_states, grads, self._fused_key,
         mets, health) = self._fused_prog(w, arg_vals, exe._aux_vals(),
                                          self._fused_key,
                                          self._fused_states,
                                          lr_arr, wd_arr)
        self._last_health = health        # device scalars (or None)
        if sa_on:
            sa_t2 = _sa.clock()
            _sa.note("dispatch", sa_t2 - sa_t1)
            jax.block_until_ready(new_w)
            _sa.note("device", _sa.clock() - sa_t2)
        self._fused_states = new_states
        self._fused_metric_scalars = [
            (m, int(np.prod(arg_vals[nm].shape)))
            for m, (_, nm) in zip(mets, self._fused_metric_pairs)]
        # the counts are valid only for THIS batch's labels: hold the
        # label objects themselves (bare id()s could be reused by the
        # allocator after the batch dies and wrongly match new labels)
        self._fused_metric_labels = list(data_batch.label or [])
        ad = exe.arg_dict
        for nm in self._fused_watched:
            ad[nm]._set(new_w[nm])
        if grads is not None:             # MXNET_FUSED_KEEP_GRADS=1
            gd = exe.grad_dict
            for nm, g in grads.items():
                dst = gd.get(nm)
                if dst is not None:
                    dst._set(g.astype(dst.dtype))
        if new_aux:
            xd = exe.aux_dict
            for nm, val in new_aux.items():
                xd[nm]._set(val)
        exe._outputs = [NDArray(o, ctx=self.contexts[0]) for o in outs]
        exe._pending = None
        if exe._sentinel is not None:
            # grads are fresh only under KEEP_GRADS (otherwise the bound
            # buffers hold the arming-time NaN poison, not real values)
            exe._sentinel.check_executor(exe, grads_fresh=grads is not None)

    # ------------------------------------------------- K-step scan dispatch
    def scan_ready(self, K):
        """Arm (or confirm) the K-step scan program; False -> the caller
        stays on the single-step path. Structural refusals: no fused
        step, MXNET_FUSED_KEEP_GRADS=1 (stacking K gradient sets would
        multiply the step's memory), or a previous arming failure."""
        if K <= 1 or getattr(self, "_step_core", None) is None:
            return False
        if self._fused_keep_grads or self._scan_failed:
            return False
        if self._scan_K == K and self._scan_prog is not None:
            return True
        try:
            self._arm_scan(K)
            return True
        except Exception as exc:
            self.logger.warning(
                "K-step scan arming failed (%s); staying single-step", exc)
            self._scan_failed = True
            return False

    def _arm_scan(self, K):
        """Build (or fetch from the program cache) the jitted program
        running K fused steps inside one ``lax.scan`` — ONE host→device
        dispatch per K batches. Params / optimizer states / rng key ride
        the carry (donated); per-step outputs and metric counts come
        back stacked as ys so metrics and callbacks still see per-batch
        numbers."""
        step_core = self._step_core
        watched = self._fused_watched

        def scan_fn(w, states, key, aux_vals, rest_static, xs):
            def body(carry, x):
                w, states, key, aux = carry
                rest = dict(rest_static)
                rest.update(x["in"])
                (outs, new_aux, new_w, new_states, _grads, key,
                 mets, health) = step_core(w, rest, aux, key, states,
                                           x["lr"], x["wd"])
                if new_aux:
                    aux = {**aux, **new_aux}
                return (new_w, new_states, key, aux), (outs, mets, health)

            w0 = w
            (w, states, key, aux), (outs_s, mets_s, health_s) = \
                jax.lax.scan(body, (w, states, key, aux_vals), xs)
            if health_s is not None:
                # window-level param stats over the K-step delta: one
                # amortised pass instead of a per-step read that would
                # break the donated in-place carry (see
                # _window_param_stats)
                health_s = _window_param_stats(health_s, w0, w, watched)
            return w, states, key, aux, outs_s, mets_s, health_s

        gkey = None
        if self._fused_cache_key is not None:
            gkey = self._fused_cache_key + ("scan", K)
            fn = _progcache.get(gkey)
            if fn is not None:
                if _telemetry.enabled():
                    _telemetry.counter("executor.jit_cache.hit").inc()
                self._scan_prog, self._scan_K = fn, K
                return
        if _telemetry.enabled():
            _telemetry.counter("executor.jit_cache.miss").inc()
        # a remat policy extends donation to the aux carry: the scan
        # body threads the FULL aux dict through the carry, so every
        # entry comes back as a (possibly aliased) output buffer and the
        # cells re-point at it — safe without the per-entry cover check
        # the single step needs
        donate = (0, 1, 2, 3) if getattr(
            self, "_remat_policy", "none") != "none" else (0, 1, 2)
        fn = _telemetry.wrap_dispatch(
            jax.jit(scan_fn, donate_argnums=donate), "scan_step")
        if gkey is not None:
            _progcache.put(gkey, fn)
        self._scan_prog, self._scan_K = fn, K

    def _place_stacked(self, arr):
        """Device-place a (K, batch, ...) stacked array: the scan axis
        stays unsharded, the batch axis shards over the mesh."""
        if self._mesh is None:
            return jax.device_put(arr, self.contexts[0].jax_device())
        if self._spmd_plan is not None:
            return jax.device_put(
                arr, self._spmd_plan.data_sharding_for(arr.shape,
                                                       stacked=True))
        return jax.device_put(arr, self._stacked_sharding)

    def _stack_window(self, window, K):
        """Per-step input dict {name: (K, batch, ...)} + per-step label
        NDArray lists, from either a StackedDataBatch (iterator already
        stacked, possibly in device memory) or a list of K DataBatches."""
        exe = self.executor
        xs_in = {}
        if hasattr(window, "steps"):            # StackedDataBatch
            slots = list(zip(self.data_names, window.data)) + \
                list(zip(self.label_names, window.label or []))
            labels_per_step = [
                [NDArray(l.asjax()[k]) for l in (window.label or [])]
                for k in range(K)]
        else:                                   # list of K DataBatches
            slots = []
            for i, name in enumerate(self.data_names):
                slots.append((name, [b.data[i] for b in window]))
            n_lab = min(len(b.label or []) for b in window)
            for i, name in enumerate(self.label_names[:n_lab]):
                slots.append((name, [b.label[i] for b in window]))
            labels_per_step = [list(b.label or []) for b in window]
        for name, val in slots:
            dst = exe.arg_dict.get(name)
            if dst is None:
                continue
            if isinstance(val, list):
                val = jnp.stack([v.asjax() if isinstance(v, NDArray)
                                 else jnp.asarray(np.asarray(v))
                                 for v in val])
            else:
                val = val.asjax() if isinstance(val, NDArray) \
                    else jnp.asarray(np.asarray(val))
            xs_in[name] = self._place_stacked(val.astype(dst.dtype))
        return xs_in, labels_per_step

    def scan_step(self, window, lrs_list, wds_list):
        """Run K fused train steps in ONE dispatch; swap the advanced
        params/states/aux/rng in, and queue per-step outputs + metric
        counts for ``advance_scan_step`` so the fit loop can still do
        per-batch bookkeeping."""
        from .. import random as _random
        _sa = _telemetry.stepattr
        sa_on = _sa.active()
        if sa_on:
            sa_t0 = _sa.clock()
        exe = self.executor
        K = len(lrs_list)
        if not self.scan_ready(K):
            raise MXNetError("scan_step called without an armed scan "
                             "program (call scan_ready(K) first)")
        if self._fused_rng_gen != _random.generation():
            # mx.random.seed() since the last dispatch: re-draw the
            # device chain at the window boundary (same rule as
            # fused_step, at window granularity)
            self._fused_key = _random.next_key()
            self._fused_rng_gen = _random.generation()
        xs_in, labels_per_step = self._stack_window(window, K)

        # lr/wd as ONE stacked (K, n_watched) device array per side,
        # cached by value — zero transfers per window on fixed schedules
        lrwd_key = (tuple(tuple(l[nm] for nm in self._fused_watched)
                          for l in lrs_list),
                    tuple(tuple(w[nm] for nm in self._fused_watched)
                          for w in wds_list))
        if self._scan_lrwd[0] != lrwd_key:
            self._scan_lrwd = (
                lrwd_key, jnp.asarray(lrwd_key[0], jnp.float32),
                jnp.asarray(lrwd_key[1], jnp.float32))
        _, lr_arr, wd_arr = self._scan_lrwd

        arg_vals = exe._arg_vals()
        w = {nm: arg_vals.pop(nm) for nm in self._fused_watched}
        rest_static = {nm: v for nm, v in arg_vals.items()
                       if nm not in xs_in}
        if sa_on:
            sa_t1 = _sa.clock()
            _sa.note("assemble", sa_t1 - sa_t0)
        (new_w, new_states, self._fused_key, new_aux, outs_s,
         mets_s, health_s) = self._scan_prog(
            w, self._fused_states, self._fused_key, exe._aux_vals(),
            rest_static, {"in": xs_in, "lr": lr_arr, "wd": wd_arr})
        self._last_health = health_s      # (K,)-stacked stats (or None)
        if sa_on:
            sa_t2 = _sa.clock()
            _sa.note("dispatch", sa_t2 - sa_t1)
            # the window boundary IS the step-attribution sync point:
            # one block per K batches, so the scan fast path keeps its
            # async pipeline shape while device time still attributes
            jax.block_until_ready(new_w)
            _sa.note("device", _sa.clock() - sa_t2)
        self._fused_states = new_states
        ad = exe.arg_dict
        for nm in self._fused_watched:
            ad[nm]._set(new_w[nm])
        xd = exe.aux_dict
        for nm, val in new_aux.items():
            if nm in xd:
                xd[nm]._set(val)
        exe._pending = None
        self._fused_metric_scalars = None

        sizes = [int(np.prod(xs_in[nm].shape[1:])) if nm in xs_in
                 else int(np.prod(exe.arg_dict[nm].shape))
                 for (_, nm) in self._fused_metric_pairs]
        self._scan_results = collections.deque(
            (k, outs_s,
             [(mets_s[j][k], sizes[j]) for j in range(len(mets_s))],
             labels_per_step[k])
            for k in range(K))
        if exe._sentinel is not None:
            # window-granularity tripwire on the final step's outputs
            # (params already advanced K steps; per-op attribution needs
            # the staged path, as with the single fused step)
            exe._outputs = [NDArray(o[K - 1], ctx=self.contexts[0])
                            for o in outs_s]
            exe._sentinel.check_executor(exe, grads_fresh=False)

    def advance_scan_step(self):
        """Expose the next scanned step's outputs/metric counts as if a
        single fused step had just run; returns that step's labels."""
        k, outs_s, scalars, labels = self._scan_results.popleft()
        exe = self.executor
        exe._outputs = [NDArray(o[k], ctx=self.contexts[0])
                        for o in outs_s]
        self._fused_metric_scalars = scalars
        self._fused_metric_labels = labels
        return labels

    # windows of undrained health stats the device may still be
    # computing; past this the oldest is forced through (bounds memory
    # and detection lag when the host runs far ahead of the device)
    _HEALTH_LAG_MAX = 4

    def take_health(self, cursor=(0, 0), flush=False):
        """Drain in-program health stats as a list of
        ``(stat_dict, epoch, nbatch)`` per-step tuples (None when
        nothing is ready / the program wasn't armed).

        Stats queue behind the dispatch that produced them and are read
        back only once the device reports them finished
        (``Array.is_ready()``) — the fit loop never hard-syncs
        mid-epoch, so an eager device_get here would block on in-flight
        windows and serialize the host behind the device (measured
        ~5-10% of a fit epoch on benchmarks/telemetry_overhead.py; the
        readiness gate makes arming free). The backlog is bounded by
        ``_HEALTH_LAG_MAX`` windows; ``flush=True`` drains everything —
        the epoch-end call, where the loop syncs anyway. ``cursor`` is
        ``(epoch, first_nbatch)`` of the just-dispatched window, handed
        back alongside its stats so observations attribute to the
        batches that produced them however late they drain."""
        q = getattr(self, "_health_queue", None)
        if q is None:
            q = self._health_queue = collections.deque()
        if self._last_health is not None:
            q.append((self._last_health, cursor))
            self._last_health = None
        out = []
        while q:
            h, (ep, nb) = q[0]
            if not flush and len(q) <= self._HEALTH_LAG_MAX:
                try:
                    # one leaf speaks for the window: every stat comes
                    # out of the same dispatch
                    if not h["grad_norm"].is_ready():
                        break
                except AttributeError:
                    pass        # host-side array: always ready
            q.popleft()
            for k, stats in enumerate(self._health_records(h) or ()):
                out.append((stats, ep, nb + k))
        return out or None

    @staticmethod
    def _health_records(h):
        """Decode one stashed health pytree into per-step host dicts.

        grad_norm / loss / nonfinite are per-step (K-stacked on the
        scan path); param_norm / update_ratio are one window-level
        reading (see ``_window_param_stats``), repeated onto each of
        the window's records so every observation carries the full
        stat set."""
        if h is None:
            return None
        vals = jax.device_get(h)
        gn = np.asarray(vals["grad_norm"])
        loss = np.asarray(vals["loss"])
        pn = float(np.asarray(vals["param_norm"]))
        ur = float(np.asarray(vals["update_ratio"]))
        if gn.ndim == 0:
            return [{"grad_norm": float(gn), "param_norm": pn,
                     "update_ratio": ur,
                     "nonfinite": float(vals["nonfinite"]),
                     "loss": [float(x) for x in np.ravel(loss)]}]
        nf = np.asarray(vals["nonfinite"])
        return [{"grad_norm": float(gn[k]), "param_norm": pn,
                 "update_ratio": ur, "nonfinite": float(nf[k]),
                 "loss": [float(x) for x in np.ravel(loss[k])]}
                for k in range(gn.shape[0])]

    # -------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params):
        """reference: executor_group.py set_params -> copy into the bound
        arrays, preserving sharded placement."""
        fused = getattr(self, "_fused_prog", None) is not None
        ad = self.executor.arg_dict
        for name, arr in arg_params.items():
            if name in ad:
                val = arr.asjax() if isinstance(arr, NDArray) \
                    else jnp.asarray(arr)
                val = self._place(val.astype(ad[name].dtype), "param",
                                  name)
                if fused and name in self._fused_watched:
                    # the fused step donates its param inputs; astype/
                    # device_put are identity when dtype+placement already
                    # match, which would alias the caller's buffer into a
                    # donated argument — force exclusive ownership, same
                    # as the arming-time copy
                    val = jnp.array(val, copy=True)
                ad[name]._set(val)
        xd = self.executor.aux_dict
        for name, arr in (aux_params or {}).items():
            if name in xd:
                val = arr.asjax() if isinstance(arr, NDArray) \
                    else jnp.asarray(arr)
                xd[name]._set(self._place(val.astype(xd[name].dtype),
                                          "param", name))

    def get_params(self, arg_params, aux_params):
        """Copy params out (device->host). reference: executor_group.py."""
        for name in self.param_names:
            arg_params[name] = self.executor.arg_dict[name].copy()
        for name in self.aux_names:
            aux_params[name] = self.executor.aux_dict[name].copy()

    # -------------------------------------------------------------- forward
    def forward(self, data_batch, is_train=None):
        """Load the full batch sharded over the mesh and run.

        reference: executor_group.py:355-379 _load_data + per-exec forward;
        here the shard happens in jax.device_put (host->HBM splits, which
        overlap with compute thanks to async dispatch).
        """
        if is_train is None:
            is_train = self.for_training
        # any staged execution invalidates fused-step metric scalars so a
        # later update_metric (e.g. an eval pass) can never consume
        # counts from a previous train batch; pending scanned steps are
        # dropped for the same reason, as are undrained health stats
        self._fused_metric_scalars = None
        self._last_health = None
        if getattr(self, "_health_queue", None):
            self._health_queue.clear()
        if getattr(self, "_scan_results", None):
            self._scan_results.clear()
        self._load_batch(data_batch)
        self.executor.forward(is_train=is_train)

    def _load_batch(self, data_batch):
        """Shard the batch's data (and labels, which eval graphs read)
        into the bound input arrays."""
        load_span = _telemetry.span("io.load_batch")

        def load(names, arrays):
            for name, arr in zip(names, arrays):
                dst = self.executor.arg_dict.get(name)
                if dst is None:
                    continue
                val = arr.asjax() if isinstance(arr, NDArray) else \
                    jnp.asarray(np.asarray(arr))
                dst._set(self._place(val.astype(dst.dtype), "data"))

        with load_span:
            load(self.data_names, data_batch.data)
            if self.label_names and data_batch.label:
                load(self.label_names, data_batch.label)

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True"
        self.executor.backward(out_grads=out_grads)

    # -------------------------------------------------------------- outputs
    def get_outputs(self, merge_multi_context=True):
        outs = self.executor.outputs
        if merge_multi_context:
            return outs
        return [[o] for o in outs]

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        grads = [self.executor.grad_dict[name] for name in self.data_names]
        if merge_multi_context:
            return grads
        return [[g] for g in grads]

    def update_metric(self, eval_metric, labels):
        """reference: executor_group.py:510 — metric on device outputs.

        After a fused step, plain Accuracy consumes the correct-counts
        the program already computed (zero extra dispatches); every
        other metric takes the general path on the outputs."""
        from ..metric import Accuracy
        scalars = getattr(self, "_fused_metric_scalars", None)
        if (scalars and type(eval_metric) is Accuracy
                and eval_metric.num is None
                and len(scalars) == len(labels or [])
                # same label/output count contract the staged path's
                # check_label_shapes enforces — never mask a violation
                and len(labels) == len(self.executor.outputs)
                # the counts belong to the fused batch's label objects;
                # a caller scoring different labels gets the general path
                and len(labels) == len(self._fused_metric_labels)
                and all(a is b for a, b in
                        zip(labels, self._fused_metric_labels))):
            self._fused_metric_scalars = None
            for correct, size in scalars:
                eval_metric._accumulate_device(correct, size)
            return
        eval_metric.update(labels, self.executor.outputs)

    def get_states(self, merge_multi_context=True):
        assert not self.state_names
        return []

    def set_states(self, states=None, value=None):
        pass

    def install_monitor(self, mon):
        mon.install_exe(self.executor)

    def install_sentinel(self, sentinel, per_op=False):
        sentinel.install(self.executor, per_op=per_op)

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        self.data_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                            for x in data_shapes]
        if label_shapes is not None:
            self.label_shapes = [x if isinstance(x, DataDesc)
                                 else DataDesc(*x) for x in label_shapes]
        self._bind_exec(shared_group)
