"""Module: the primary training interface over one Symbol.

Behavioral parity with reference python/mxnet/module/module.py, written
for this framework's execution model: ONE mesh-sharded executor instead
of a list of per-device executors, so parameter handling is a flat
name->NDArray mapping throughout and the update path walks
``zip(param_names, param_arrays, grad_arrays)`` with stride 1.
"""
from __future__ import annotations

import logging
import pickle

import numpy as np

from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..context import current_context
from ..initializer import Uniform
from ..model import (_create_kvstore, _initialize_kvstore, load_checkpoint)
from ..ndarray import NDArray
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    """Train/predict over a single Symbol bound to a (possibly multi-
    device) context list. reference: module/module.py:40-700."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, compute_dtype=None):
        super().__init__(logger=logger)
        self._compute_dtype = compute_dtype
        context = context if context is not None else [current_context()]
        self._context = list(context) if isinstance(context, (list, tuple)) \
            else [context]
        self._work_load_list = work_load_list or [1] * len(self._context)

        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._state_names = list(state_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._output_names = symbol.list_outputs()
        self._aux_names = symbol.list_auxiliary_states()
        inputs = set(self._data_names) | set(self._label_names)
        self._param_names = [a for a in symbol.list_arguments()
                             if a not in inputs]
        _check_input_names(symbol, self._data_names, "data", True)
        _check_input_names(symbol, self._label_names, "label", False)
        _check_input_names(symbol, self._state_names, "state", True)
        _check_input_names(symbol, self._fixed_param_names, "fixed_param",
                           True)

        self._exec_group = None
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._grad_req = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._fused_armed = False
        self._fused_done = False
        self._steps_per_dispatch = 1
        self._zero_stage = None         # None -> MXNET_ZERO_STAGE, else 0
        self._spmd = None               # None -> MXNET_SPMD at bind time
        self._mesh_config = None        # parallel.MeshConfig (spmd mode)
        self._remat = None              # None -> MXNET_REMAT_POLICY

    # ------------------------------------------------------------ checkpoint
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Build a Module from a saved checkpoint (symbol JSON + params)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params, mod._aux_params = args, auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Write prefix-symbol.json + prefix-NNNN.params (+ .states)."""
        self._symbol.save(f"{prefix}-symbol.json")
        self.save_params(f"{prefix}-{epoch:04d}.params")
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    # ------------------------------------------------------------ properties
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        known = {d.name: d.shape for d in self._exec_group.data_shapes}
        for l in self._exec_group.label_shapes or []:
            known[l.name] = l.shape
        _, out_shapes, _ = self._symbol.infer_shape(**known)
        return list(zip(self._output_names, out_shapes))

    # ---------------------------------------------------------------- params
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return self._arg_params, self._aux_params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False):
        """Fill parameter arrays from the caches and/or the initializer."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "bind() must run before init_params()"

        exe = self._exec_group.executor
        if self._arg_params is None:
            self._arg_params = {
                n: nd.zeros(exe.arg_dict[n].shape,
                            dtype=exe.arg_dict[n].dtype)
                for n in self._param_names}
        if self._aux_params is None:
            self._aux_params = {
                n: nd.zeros(a.shape, dtype=a.dtype)
                for n, a in exe.aux_dict.items()}

        def fill(name, arr, cache):
            if cache is None:
                initializer(name, arr)
            elif name in cache:
                src = cache[name]
                if src is not arr:
                    if isinstance(src, NDArray):
                        src.copyto(arr)
                    else:
                        arr._set(np.asarray(src))
            elif not allow_missing:
                raise RuntimeError(
                    f"parameter {name!r} missing from the provided params "
                    "(pass allow_missing=True to initialize it instead)")
            elif initializer is not None:
                initializer(name, arr)

        for name in sorted(self._arg_params):
            fill(name, self._arg_params[name], arg_params)
        for name in sorted(self._aux_params):
            fill(name, self._aux_params[name], aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=False,
                             force_init=force_init)
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self.params_initialized = True

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    # ------------------------------------------------------------------ bind
    def _resolve_spmd(self, explicit=None):
        """SPMD mode: explicit bind arg > fit kwarg (self._spmd) >
        MXNET_SPMD env; default off (the kvstore-era arrangement)."""
        import os
        if explicit is not None:
            return bool(explicit)
        if self._spmd is not None:
            return bool(self._spmd)
        return os.environ.get("MXNET_SPMD", "").lower() in \
            ("1", "true", "yes", "on")

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write", spmd=None, mesh=None):
        """Compile the symbol into the sharded executor group.

        ``spmd=True`` (or ``MXNET_SPMD=1`` / ``fit(spmd=True)``) binds
        the GSPMD arrangement: one program over the named mesh from
        ``mesh`` (a ``parallel.MeshConfig``; default ``MXNET_MESH_*``
        env, else a 1-D data axis over the contexts), params sharded per
        the symbol's ctx_group tags on the model axis, gradient
        collectives emitted by XLA from the sharding specs — the
        kvstore becomes optional (docs/performance.md).
        """
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Module is already bound; ignoring bind() "
                                "(use force_rebind=True to re-bind)")
            return
        if not for_training:
            assert not inputs_need_grad

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req
        if mesh is not None:
            self._mesh_config = mesh
        self._spmd_active = self._resolve_spmd(spmd)

        shared_group = None
        if shared_module is not None:
            assert isinstance(shared_module, Module) and \
                shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names,
            compute_dtype=self._compute_dtype,
            spmd=self._spmd_active, mesh_config=self._mesh_config)

        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._exec_group.bind_exec(data_shapes, label_shapes, reshape=True)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    # ------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Resolve the kvstore/updater arrangement and build the optimizer."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer is already initialized; "
                                "ignoring init_optimizer()")
            return

        # SPMD mode: the gradient collectives live inside the jitted
        # program (XLA emits them from the sharding specs) — a local/
        # device kvstore would be a second, redundant reduction plan, so
        # it is dropped; dist_* stores keep owning cross-process
        # reduction (the mesh here is single-process) and disable spmd's
        # in-program arrangement via the normal fused-step gating.
        spmd_plan = getattr(self._exec_group, "_spmd_plan", None)
        if spmd_plan is not None and kvstore is not None:
            kv_type = kvstore if isinstance(kvstore, str) \
                else getattr(kvstore, "type", "")
            if "dist" in kv_type:
                self.logger.warning(
                    "spmd mode with a %r kvstore: cross-process "
                    "reduction stays on the kvstore path (the in-program "
                    "collectives cover this process's mesh only)", kv_type)
            else:
                self.logger.info(
                    "spmd mode: %r kvstore dropped — gradient "
                    "collectives are emitted by XLA from the mesh "
                    "sharding specs", kv_type)
                kvstore = None

        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        # dist_sync semantics: every worker sees the global batch
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers

        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            params.setdefault("rescale_grad", 1.0 / batch_size)
            optimizer = opt.create(
                optimizer, sym=self.symbol,
                param_idx2name=dict(enumerate(self._param_names)),
                **params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None if update_on_kvstore \
            else opt.get_updater(optimizer)

        # Fused train step: forward+backward+update as ONE XLA program
        # (reference bulk-exec segments + fused optimizer_op.cc). Armed
        # only when the update is single-process local — a dist kvstore
        # or server-side updater owns the math in those arrangements.
        # zero_stage=1 (fit kwarg or MXNET_ZERO_STAGE) selects the
        # in-program reduce-scatter + sharded-state update plan.
        import os
        zero_stage = self._zero_stage
        if zero_stage is None:
            zero_stage = int(os.environ.get("MXNET_ZERO_STAGE", "0") or 0)
        self._fused_armed = False
        self._fused_done = False
        if (not update_on_kvstore
                and (kvstore is None or "dist" not in kvstore.type)
                and self._exec_group.executor._monitor_callback is None):
            self._fused_armed = bool(
                self._exec_group.setup_fused_step(optimizer,
                                                  zero_stage=zero_stage,
                                                  remat=self._remat))
        if spmd_plan is not None and not self._fused_armed:
            self.logger.warning(
                "spmd requested but the fused train step could not arm "
                "(monitor/NaiveEngine/non-fusable optimizer or grad_req, "
                "or a dist kvstore); the staged per-phase path runs over "
                "the mesh instead")

        if kvstore:
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
            if update_on_kvstore:
                kvstore.set_optimizer(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

        # the donation/collective hazard surface only exists once the
        # fused/ZeRO plans are armed and the kvstore is attached —
        # re-run the static-analysis passes over the full arrangement
        # (MXNET_GRAPH_VALIDATE=warn|raise; bind() already verified the
        # bare graph)
        from .. import analysis as _analysis
        if _analysis.resolve_mode(None) is not None:
            _analysis.validate_module(self)

    def borrow_optimizer(self, shared_module):
        """Share optimizer state with another Module (bucketing)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        # shared optimizer state lives in the updater — the fused path
        # keeps per-group device state, so bucketing stays staged
        self._fused_armed = False
        self._fused_done = False
        self.optimizer_initialized = True

    # ------------------------------------------------------------ train step
    def forward_backward(self, data_batch):
        """One training pass; routes through the fused fwd+bwd+update
        program when armed. The weight update then happens inside this
        call (the subsequent ``update()`` is a no-op for the batch), so
        a loop that conditionally skips ``update()`` must first disarm
        with ``install_monitor`` absent via the staged path. The fused
        program does not emit per-param gradients (they cost ~5% of the
        step as extra XLA outputs); set ``MXNET_FUSED_KEEP_GRADS=1`` to
        keep ``grad_dict`` populated, or install a monitor to fall back
        to the staged path, which always populates it."""
        if self._fused_armed and self.optimizer_initialized:
            if self._exec_group.executor._monitor_callback is not None:
                # a monitor was installed directly on the executor after
                # arming — migrate to the staged path for good so the
                # optimizer state lives in exactly one place
                self._defuse()
            else:
                self._exec_group.fused_step(data_batch,
                                            *self._fused_lr_wd())
                self._fused_done = True
                return
        self.forward(data_batch, is_train=True)
        self.backward()

    def _fused_lr_wd(self):
        """Per-step host-side lr/wd per watched param (scheduler, mults,
        Adam bias correction) — the traced scalars the fused program
        takes each dispatch. Ordering matches the staged Optimizer
        .update: lr/wd are read BEFORE the update count advances, the
        bias-correction step count after."""
        o = self._optimizer
        watched = set(self._exec_group._fused_watched)
        lrs, wds = {}, {}
        scale = getattr(o, "fused_lr_scale", None)
        for i, nm in enumerate(self._param_names):
            if nm not in watched:
                continue
            lr = o._get_lr(i)
            wds[nm] = o._get_wd(i)
            o._update_count(i)
            if scale is not None:
                lr *= scale(o._index_update_count[i])
            lrs[nm] = lr
        return lrs, wds

    def _defuse(self):
        """Disarm the fused path, migrating its device optimizer state
        into the staged updater so training numerics continue exactly
        (ZeRO-sharded states unflatten back to param shape first)."""
        import jax
        fs = self._exec_group.defused_states()
        for i, nm in enumerate(self._param_names):
            if nm not in fs:
                continue
            leaves = jax.tree.leaves(fs[nm])
            if not leaves:
                state = None
            elif isinstance(fs[nm], (tuple, list)):
                state = tuple(NDArray(l) for l in leaves)
            else:
                state = NDArray(leaves[0])
            self._updater.states[i] = state
        self._fused_armed = False

    # --------------------------------------------------- K-step scan window
    def _scan_window_size(self):
        """Batches per dispatch for the scan-fused fit loop (1 = the
        plain per-batch loop). >1 only when the fused step is armed, no
        monitor claims per-op taps, and the scan program arms."""
        K = getattr(self, "_steps_per_dispatch", 1)
        if K <= 1 or not self._fused_armed or not self.optimizer_initialized:
            return 1
        if self._exec_group.executor._monitor_callback is not None:
            return 1
        if not self._exec_group.scan_ready(K):
            return 1
        return K

    def _run_scan_window(self, window):
        """Advance K batches in one scan dispatch. lr/wd/update-counts
        are read per step host-side first (identical scheduler semantics
        to K single fused steps), then the whole window executes as one
        XLA program."""
        K = window.steps if hasattr(window, "steps") else len(window)
        lrs_list, wds_list = [], []
        for _ in range(K):
            lrs, wds = self._fused_lr_wd()
            lrs_list.append(lrs)
            wds_list.append(wds)
        self._exec_group.scan_step(window, lrs_list, wds_list)
        self._params_dirty = True

    def _advance_scan_batch(self):
        return self._exec_group.advance_scan_step()

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply one optimizer step to every trainable parameter.

        Two arrangements (reference model.py:88-116 semantics, flat here):
        update_on_kvstore — push grad / pull weight, the store's updater
        does the math; otherwise — optional kvstore grad all-reduce, then
        the local updater writes the weights in place.
        """
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        if self._fused_done:
            # weights/state already advanced inside the fused program
            self._fused_done = False
            return
        if self._fused_armed:
            # caller is driving forward/backward/update manually (e.g.
            # BucketingModule) — migrate to the staged arrangement so
            # optimizer state lives in exactly one place
            self._defuse()
        weights = self._exec_group.param_arrays
        grads = self._exec_group.grad_arrays
        idxs = [i for i, g in enumerate(grads) if g is not None]
        if not idxs:
            return
        if self._kvstore:
            # ONE multi-key push in reverse execution order — the order
            # backward produces gradients — with matching priorities, so
            # the dist store's bucket scheduler dispatches each bucket's
            # collective as soon as its grads exist (overlapping with
            # the still-draining backward program) instead of one
            # serial reduce per key. Pulls then run forward-order
            # (priority=-i): early layers land first for the next
            # forward, the reference's pull-priority contract.
            rev = idxs[::-1]
            self._kvstore.push(rev, [grads[i] for i in rev],
                               priority=rev)
            if self._update_on_kvstore:
                self._kvstore.pull(idxs, [weights[i] for i in idxs],
                                   priority=[-i for i in idxs])
                return
            self._kvstore.pull(idxs, [grads[i] for i in idxs],
                               priority=[-i for i in idxs])
        if self._update_on_kvstore:
            # update_on_kvstore without a store cannot happen
            # (_create_kvstore forces it False when kv is None)
            raise MXNetError("update_on_kvstore set without a kvstore")
        for i in idxs:
            self._updater(i, grads[i], weights[i])

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    # ------------------------------------------------------ optimizer states
    def _opt_counts(self):
        """Name-keyed update counts + the global count — the half of the
        optimizer's state that is NOT per-param arrays (Adam bias
        correction, lr schedules). Without these a restored run replays
        update 1's bias correction and warmup lr over trained weights."""
        o = self._optimizer
        return {
            "num_update": int(o.num_update),
            "index_update_count": {
                self._param_names[i]: int(c)
                for i, c in o._index_update_count.items()
                if 0 <= i < len(self._param_names)},
        }

    def _restore_opt_counts(self, counts):
        o = self._optimizer
        o.num_update = int(counts.get("num_update", o.num_update))
        idx = {nm: i for i, nm in enumerate(self._param_names)}
        for nm, c in (counts.get("index_update_count") or {}).items():
            if nm in idx:
                o._index_update_count[idx[nm]] = int(c)

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
            return
        def host(v):
            if isinstance(v, NDArray):
                return v.asnumpy()
            if isinstance(v, (tuple, list)):
                return [host(x) for x in v]
            return v
        if self._fused_armed:
            # export always writes param-shaped host arrays: replicated
            # and ZeRO-sharded arrangements produce the same checkpoint
            states = {"__fused__": self._exec_group.export_fused_states()}
        else:
            states = {k: host(v) for k, v in self._updater.states.items()}
        payload = {"__format__": 2, "states": states,
                   **self._opt_counts()}
        with open(fname, "wb") as fout:
            pickle.dump(payload, fout)

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as fin:
            states = pickle.load(fin)
        if isinstance(states, dict) and states.get("__format__") == 2:
            self._restore_opt_counts(states)
            states = states["states"]
        import jax
        if "__fused__" in states and self._fused_armed:
            self._exec_group.import_fused_states(states["__fused__"])
        elif "__fused__" in states:
            # fused-format checkpoint into a staged module: unwrap to the
            # updater's per-index states
            for i, nm in enumerate(self._param_names):
                if nm not in states["__fused__"]:
                    continue
                leaves = jax.tree.leaves(states["__fused__"][nm])
                if not leaves:
                    st = None
                elif isinstance(states["__fused__"][nm], (tuple, list)):
                    st = tuple(NDArray(jnp_arr) for jnp_arr in
                               map(np.asarray, leaves))
                else:
                    st = NDArray(np.asarray(leaves[0]))
                self._updater.states[i] = st
        elif self._fused_armed:
            # staged-format checkpoint into a fused module: project each
            # per-index state onto the fused per-name device layout
            # (replicated or ZeRO-sharded; pickled staged tuples come
            # back as lists — import_staged_state walks the structure)
            fs = self._exec_group._fused_states
            for i, nm in enumerate(self._param_names):
                if nm in fs and i in states and jax.tree.leaves(fs[nm]):
                    self._exec_group.import_staged_state(nm, states[i])
        else:
            self._updater.states.update(states)

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)
        if self._fused_armed:
            # per-op taps need the staged path; carry the optimizer
            # state over so momentum/moments don't reset
            self._defuse()

    def install_sentinel(self, sentinel, per_op=False):
        """Attach a NaN/Inf sentinel (telemetry.NanSentinel) to the bound
        executor. The default executor-level mode works on the fused
        train step; ``per_op=True`` claims the Monitor tap for exact
        op attribution, which forces the staged (eager) path."""
        assert self.binded
        self._exec_group.install_sentinel(sentinel, per_op=per_op)
        if per_op and self._fused_armed:
            self._defuse()
