"""PythonModule / PythonLossModule: module-shaped python computations.

API parity with reference python/mxnet/module/python_module.py. These
carry no parameters and no executor; they exist so python-side logic
(custom losses, metric heads) can slot into a SequentialModule chain or
be driven by the fit loop. PythonModule supplies the no-op plumbing;
subclasses implement ``forward``/``backward``/``_compute_output_shapes``.
"""
from __future__ import annotations

import logging

from ..ndarray import NDArray, array
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Parameter-free module shell: bind records shapes, params/optimizer
    are no-ops, update_metric runs on whatever forward produced."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._output_names = list(output_names or [])
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # no parameters to manage
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is not None:
            eval_metric.update(labels, self.get_outputs())

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Module is already bound; ignoring bind()")
            return
        if grad_req != "write":
            raise ValueError("PythonModule only supports grad_req='write'")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError()

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """Identity forward + user-supplied gradient: the terminal loss stage
    of a SequentialModule chain.

    ``grad_func(scores, labels) -> grad`` defines the backward; forward
    passes scores through unchanged (like MakeLoss).
    """

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        if len(data_names) != 1 or len(label_names) != 1:
            raise ValueError("PythonLossModule takes exactly one data and "
                             "one label input")
        super().__init__(data_names, label_names, [f"{name}_output"],
                         logger=logger)
        self._name = name
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func
        self._scores = None
        self._labels = None
        self._scores_grad = None

    def _compute_output_shapes(self):
        d = self._data_shapes[0]
        shape = d.shape if hasattr(d, "shape") else d[1]
        return [(f"{self._name}_output", shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores]

    def backward(self, out_grads=None):
        if out_grads is not None:
            raise ValueError("a loss stage takes no upstream out_grads")
        assert self.for_training
        if self._grad_func is None:
            raise NotImplementedError(
                "pass grad_func= or subclass and override backward()")
        grad = self._grad_func(self._scores, self._labels)
        self._scores_grad = grad if isinstance(grad, NDArray) else array(grad)

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]
