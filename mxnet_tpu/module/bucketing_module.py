"""BucketingModule: variable-length sequence training by graph
specialization.

API parity with reference python/mxnet/module/bucketing_module.py; here
every bucket is its own jitted XLA program (compiled on first use) and
all buckets alias the SAME parameter NDArray cells as the default
bucket's module — no weight copying on bucket switch, the property the
reference engineers via shared memory pools. The jit cache keyed by
bucket is the "bucketed jit caches" design (SURVEY.md §7 M5).

Structure: the *leader* module (default bucket) owns parameters and the
optimizer; the *active* module is whatever bucket the last batch
selected; everything user-facing proxies to one of those two.
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise ValueError("BucketingModule needs a default_bucket_key")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._module_kwargs = dict(
            logger=logger, context=context, work_load_list=work_load_list,
            fixed_param_names=fixed_param_names, state_names=state_names)
        self._buckets = {}
        self._active_key = None
        self._params_dirty = False

    # ---------------------------------------------------------- plumbing
    def _generate(self, bucket_key):
        ret = self._sym_gen(bucket_key)
        if len(ret) != 3:
            raise ValueError(
                "sym_gen(bucket_key) must return (symbol, data_names, "
                "label_names)")
        return ret

    @property
    def _leader(self):
        return self._buckets[self._default_bucket_key]

    @property
    def _active(self):
        return self._buckets[self._active_key]

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._active_key = None

    # -------------------------------------------------------- properties
    @property
    def data_names(self):
        if self.binded:
            return self._active.data_names
        return self._generate(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._active.output_names
        return self._generate(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._active.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._active.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._active.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._active.symbol

    # ------------------------------------------------------------ params
    def get_params(self):
        assert self.binded and self.params_initialized
        self._active._params_dirty = self._params_dirty
        params = self._active.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        from ..initializer import Uniform
        self._leader.init_params(
            initializer=initializer or Uniform(0.01),
            arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init)
        self.params_initialized = True
        self._params_dirty = False

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    # -------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if shared_module is not None:
            raise ValueError("BucketingModule cannot itself be shared")
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Module is already bound; ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req

        sym, data_names, label_names = self._generate(
            self._default_bucket_key)
        leader = Module(sym, data_names, label_names, **self._module_kwargs)
        leader.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = leader
        self._active_key = self._default_bucket_key

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Select (compiling on first use) the module for ``bucket_key``."""
        assert self.binded, "bind() must run before switch_bucket()"
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._generate(bucket_key)
            mod = Module(sym, data_names, label_names,
                         **self._module_kwargs)
            mod.bind(data_shapes, label_shapes, self.for_training,
                     self.inputs_need_grad, shared_module=self._leader,
                     grad_req=self._grad_req)
            if self.optimizer_initialized:
                mod.borrow_optimizer(self._leader)
            self._buckets[bucket_key] = mod
        self._active_key = bucket_key

    def warm_buckets(self, bucket_shapes):
        """Bind every bucket in ``bucket_shapes`` up front.

        ``bucket_shapes``: iterable of ``(bucket_key, data_shapes,
        label_shapes)`` triples. Serving warmup calls this so every rung
        of a bucket ladder is bound (and its forward program traced on
        first use through the process-wide program cache) before the
        first request arrives — bucket switches in steady state then
        never construct executors or compile. Restores the previously
        active bucket. Returns the list of bucket keys bound."""
        assert self.binded and self.params_initialized, \
            "bind() + init_params() must run before warm_buckets()"
        prev = self._active_key
        bound = []
        for key, data_shapes, label_shapes in bucket_shapes:
            self.switch_bucket(key, data_shapes, label_shapes)
            bound.append(key)
        self._active_key = prev
        return bound

    @property
    def bucket_keys(self):
        """Keys with a bound module (the warmed rungs)."""
        return list(self._buckets)

    # --------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer is already initialized; "
                                "ignoring init_optimizer()")
            return
        self._leader.init_optimizer(kvstore, optimizer, optimizer_params,
                                    force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._leader:
                mod.borrow_optimizer(self._leader)
        self.optimizer_initialized = True

    # -------------------------------------------------------- train step
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._active.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._active.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._active.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._active.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._active.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._active.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for mod in self._buckets.values():
            mod.install_monitor(mon)
