"""SequentialModule: run a list of modules head-to-tail.

API parity with reference python/mxnet/module/sequential_module.py
(``add(module, take_labels=..., auto_wiring=...)`` then the usual
BaseModule surface). Forward threads each stage's outputs into the next
stage's data; backward threads input-gradients in reverse. Stages are
bound with ``inputs_need_grad=True`` for every stage after the first so
the gradient chain is closed.
"""
from __future__ import annotations

import copy
import logging

from ..io import DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._stages = []          # [(module, meta_dict)]
        self._label_shapes = None

    # backward-compat views (the reference exposes parallel lists)
    @property
    def _modules(self):
        return [m for m, _ in self._stages]

    @property
    def _metas(self):
        return [meta for _, meta in self._stages]

    def add(self, module, **kwargs):
        """Append a stage. Recognized meta: take_labels, auto_wiring."""
        valid = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}
        unknown = set(kwargs) - valid
        if unknown:
            raise ValueError(f"unknown stage meta {sorted(unknown)}; "
                             f"valid: {sorted(valid)}")
        self._stages.append((module, kwargs))
        # adding a stage invalidates any previous binding
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # -------------------------------------------------------- properties
    @property
    def data_names(self):
        return self._stages[0][0].data_names if self._stages else []

    @property
    def output_names(self):
        return self._stages[-1][0].output_names if self._stages else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._stages[0][0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._stages[-1][0].output_shapes

    # ------------------------------------------------------------ params
    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module, _ in self._stages:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        from ..initializer import Uniform
        for module, _ in self._stages:
            module.init_params(initializer=initializer or Uniform(0.01),
                               arg_params=arg_params, aux_params=aux_params,
                               allow_missing=True, force_init=force_init)
        # a param name appearing in two stages would silently fork state
        seen = {}
        for i, (module, _) in enumerate(self._stages):
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                if name in seen:
                    raise ValueError(
                        f"parameter {name!r} defined by both stage "
                        f"{seen[name]} and stage {i}")
                seen[name] = i
        self.params_initialized = True

    # -------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Module is already bound; ignoring bind()")
            return
        if inputs_need_grad:
            assert for_training
        assert shared_module is None, \
            "SequentialModule does not support shared_module"
        assert self._stages, "no stages added"

        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        flowing = list(data_shapes)
        labels_used = False
        for i, (module, meta) in enumerate(self._stages):
            takes_labels = meta.get(self.META_TAKE_LABELS, False)
            labels_used |= takes_labels
            if meta.get(self.META_AUTO_WIRING, False):
                # rename the flowing outputs to this stage's input names
                names = module.data_names
                assert len(names) == len(flowing)
                flowing = [DataDesc(nm, d.shape)
                           for nm, d in zip(names, flowing)]
            module.bind(
                data_shapes=flowing,
                label_shapes=label_shapes if takes_labels else None,
                for_training=for_training,
                # interior stages must produce input grads to keep the
                # chain rule flowing backward
                inputs_need_grad=bool(for_training and
                                      (inputs_need_grad or i > 0)),
                force_rebind=force_rebind, grad_req=grad_req)
            flowing = [DataDesc(nm, shape)
                       for nm, shape in module.output_shapes]

        self._label_shapes = label_shapes if labels_used else None

    # --------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer is already initialized; "
                                "ignoring init_optimizer()")
            return
        for module, _ in self._stages:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # -------------------------------------------------------- train step
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = copy.copy(data_batch)
        for i, (module, _) in enumerate(self._stages):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._stages):
                break
            outs = module.get_outputs()
            batch.data = outs
            if hasattr(batch, "provide_data"):
                batch.provide_data = [
                    DataDesc(nm, o.shape)
                    for nm, o in zip(module.output_names, outs)]

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i in reversed(range(len(self._stages))):
            module = self._stages[i][0]
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        for module, _ in self._stages:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._stages[-1][0].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._stages[0][0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        for module, meta in self._stages:
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for module, _ in self._stages:
            module.install_monitor(mon)
