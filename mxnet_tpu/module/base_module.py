"""BaseModule: the abstract train/evaluate/predict interface.

API parity with reference python/mxnet/module/base_module.py — ``fit``
runs bind -> init_params -> init_optimizer -> per-batch
forward_backward/update/update_metric with the same callback hook points
— reorganized here into small helpers (`_prepare_fit`, `_fit_epoch`)
around the single-executor design. Subclasses implement the narrow
abstract surface at the bottom.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as np

from .. import metric as metric_mod
from .. import ndarray as nd
from .. import telemetry as _telemetry
from ..model import BatchEndParam

__all__ = ["BaseModule"]


def _as_list(obj):
    return obj if isinstance(obj, list) else [obj]


def _fire(callbacks, param):
    for cb in _as_list(callbacks):
        cb(param)


def _check_input_names(symbol, names, typename, throw):
    """Verify user-declared input names exist among the symbol's args."""
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        non_params = [a for a in args
                      if not a.split("_")[-1] in
                      ("weight", "bias", "gamma", "beta")]
        msg = (f"{typename} name {name!r} is not an argument of the symbol "
               f"(free inputs are: {non_params})")
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class BaseModule:
    """Shared high-level driver; subclasses provide the executor plumbing."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ------------------------------------------------------------- training
    def forward_backward(self, data_batch):
        """One fused fwd+bwd pass (the hot call of fit)."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def _prepare_fit(self, train_data, initializer, arg_params, aux_params,
                     allow_missing, force_rebind, force_init, kvstore,
                     optimizer, optimizer_params, monitor):
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        self._mfu_profile = self._build_mfu_profile(train_data)

    def _build_mfu_profile(self, train_data):
        """(train FLOPs/batch, peak FLOP/s or None) from the op cost
        metadata + the optimizer update — the per-batch MFU gauge's
        numerator and denominator (telemetry/mfu.py). Best-effort:
        anything missing (no symbol, partial shapes) disables the gauge
        rather than guessing."""
        try:
            sym = getattr(self, "_symbol", None) or self.symbol
            if sym is None:
                return None
            shapes = {nm: tuple(s) for nm, s in
                      list(train_data.provide_data) +
                      list(train_data.provide_label or [])}
            table = _telemetry.mfu.cost_table(sym, shapes, train=True)
            flops = table["train_flops"]
            if not flops:
                return None
            opt = getattr(self, "_optimizer", None)
            if opt is not None:
                from ..ops.cost import optimizer_flops
                n_params = sum(
                    int(np.prod(a.shape)) for a in
                    (getattr(self, "_arg_params", None) or {}).values())
                flops += optimizer_flops(type(opt).__name__, n_params)
            peak, _bw = _telemetry.mfu.device_peaks()
            _telemetry.mfu.record_gauges(table, train=True)
            return flops, peak
        except Exception:
            return None

    def _scan_window_size(self):
        """Batches advanced per device dispatch by the fit loop; 1 means
        the plain per-batch loop. Module overrides this with the K-step
        scan-fused arrangement (module.fit steps_per_dispatch)."""
        return 1

    @staticmethod
    def _iter_with_data_wait(train_data):
        """Iterate ``train_data``, banking the time each ``next()``
        blocks (the PrefetchingIter handoff) into the step-attribution
        plane as the upcoming step's ``data_wait`` phase. One branch
        per batch when attribution is off."""
        it = iter(train_data)
        sa = _telemetry.stepattr
        while True:
            if sa.armed():
                t0 = sa.clock()
                try:
                    batch = next(it)
                except StopIteration:
                    return
                sa.note_data_wait(sa.clock() - t0)
            else:
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    def _fit_epoch(self, epoch, train_data, eval_metric, batch_end_callback,
                   monitor, skip=0):
        K = self._scan_window_size()
        if K > 1 and monitor is None:
            return self._fit_epoch_scan(epoch, train_data, eval_metric,
                                        batch_end_callback, K, skip=skip)
        sa = _telemetry.stepattr
        nbatch = -1
        for nbatch, batch in enumerate(
                self._iter_with_data_wait(train_data)):
            if nbatch < skip:
                # resume fast-forward: these batches already trained
                # before the kill; consuming them keeps the data stream
                # (and any restored shuffle rng) aligned with the
                # uninterrupted run
                sa.clear_pending_wait()
                continue
            if monitor is not None:
                monitor.tic()
            sa.step_begin(epoch, nbatch)
            batch_span = _telemetry.span(
                "module.fit.batch", _hist="module.fit.batch.seconds",
                epoch=epoch, nbatch=nbatch)
            t0 = time.perf_counter_ns()
            with batch_span:
                self.forward_backward(batch)
                self.update()
            if _telemetry.enabled():
                _telemetry.counter("module.fit.batches").inc()
                _telemetry.record_event(
                    "batch_end", epoch=epoch, nbatch=nbatch,
                    duration_us=batch_span.dur,
                    batch_size=getattr(train_data, "batch_size", 0))
                self._note_mfu(batch_span.dur)
            else:
                # the span tracer is off (the production default) — the
                # always-on flight ring still gets a batch timeline so a
                # crash report can show what the run was doing
                _telemetry.flightrec.note(
                    "module.fit.batch", epoch=epoch, nbatch=nbatch,
                    dur_us=(time.perf_counter_ns() - t0) // 1000,
                    batch_size=getattr(train_data, "batch_size", 0))
            self._health_tick(epoch, nbatch)
            self.update_metric(eval_metric, batch.label)
            sa.step_end()
            if monitor is not None:
                monitor.toc_print()
            if batch_end_callback is not None:
                _fire(batch_end_callback,
                      BatchEndParam(epoch=epoch, nbatch=nbatch,
                                    eval_metric=eval_metric,
                                    locals=locals()))
            self._ckpt_tick(epoch, nbatch)
        # epoch end: release the one-boundary health-stat lag
        self._health_tick(epoch, nbatch + 1, steps=0, flush=True)

    def _fit_epoch_scan(self, epoch, train_data, eval_metric,
                        batch_end_callback, K, skip=0):
        """Windowed epoch: K batches per device dispatch via the scan-
        fused program. Metrics, telemetry and callbacks still advance
        per logical batch — the per-step counts/outputs come back
        stacked from the one dispatch. Partial tail windows (and any
        window the scan can't take) fall back to single fused steps.
        Checkpoints are cut at window boundaries only (a snapshot
        mid-window has no consistent cursor — the K steps retire as one
        dispatch), so a resume ``skip`` is normally a multiple of K;
        a residue (checkpoint cut at a tail single) fast-forwards
        through split singles."""
        from ..io import StackedDataBatch
        nbatch = 0
        to_skip = int(skip)
        batch_size = getattr(train_data, "batch_size", 0)
        sa = _telemetry.stepattr

        def run_single(batch):
            nonlocal nbatch, to_skip
            if to_skip > 0:
                to_skip -= 1
                nbatch += 1
                sa.clear_pending_wait()
                return
            sa.step_begin(epoch, nbatch)
            t0 = time.perf_counter_ns()
            batch_span = _telemetry.span(
                "module.fit.batch", _hist="module.fit.batch.seconds",
                epoch=epoch, nbatch=nbatch)
            with batch_span:
                self.forward_backward(batch)
                self.update()
            self._note_batch(epoch, nbatch, batch_span.dur or
                             (time.perf_counter_ns() - t0) // 1000,
                             batch_size)
            self._health_tick(epoch, nbatch)
            self.update_metric(eval_metric, batch.label)
            sa.step_end()
            if batch_end_callback is not None:
                _fire(batch_end_callback,
                      BatchEndParam(epoch=epoch, nbatch=nbatch,
                                    eval_metric=eval_metric,
                                    locals=locals()))
            self._ckpt_tick(epoch, nbatch)
            nbatch += 1

        def run_window(window, steps):
            nonlocal nbatch, to_skip
            if to_skip >= steps:
                to_skip -= steps
                nbatch += steps
                sa.clear_pending_wait()
                return
            if to_skip > 0:
                # cursor inside this window: fast-forward the remainder
                # as split singles (resume replays them through the
                # single fused step — same numerics, docs/checkpoint.md)
                singles = window.split() if hasattr(window, "split") \
                    else list(window)
                for b in singles:
                    run_single(b)
                return
            sa.step_begin(epoch, nbatch)
            t0 = time.perf_counter_ns()
            win_span = _telemetry.span(
                "module.fit.window", _hist="module.fit.window.seconds",
                epoch=epoch, nbatch=nbatch, steps=steps)
            with win_span:
                self._run_scan_window(window)
            # stash this window's K-stacked health stats and drain the
            # previous window's (one-boundary lag: its device work is
            # done, so the read never stalls the async scan dispatch)
            self._health_tick(epoch, nbatch, steps)
            dur_us = win_span.dur or (time.perf_counter_ns() - t0) // 1000
            for _ in range(steps):
                labels = self._advance_scan_batch()
                self._note_batch(epoch, nbatch, dur_us // steps,
                                 batch_size)
                self.update_metric(eval_metric, labels)
                if batch_end_callback is not None:
                    _fire(batch_end_callback,
                          BatchEndParam(epoch=epoch, nbatch=nbatch,
                                        eval_metric=eval_metric,
                                        locals=locals()))
                nbatch += 1
            # one attribution record per window: phases divide over the
            # K logical batches it retired
            sa.step_end(steps=steps)
            # checkpoint/dead-node boundary once per retired window —
            # the only consistent cursor under scan dispatch
            self._ckpt_tick(epoch, nbatch - 1)

        pending = []
        for batch in self._iter_with_data_wait(train_data):
            if isinstance(batch, StackedDataBatch):
                if batch.steps == K:
                    run_window(batch, K)
                else:                       # partial tail window
                    for b in batch.split():
                        run_single(b)
            else:
                pending.append(batch)
                if len(pending) == K:
                    run_window(pending, K)
                    pending = []
        for b in pending:                   # partial tail window
            run_single(b)
        # epoch end: release the one-boundary health-stat lag
        self._health_tick(epoch, nbatch, steps=0, flush=True)

    def _note_mfu(self, dur_us):
        """Model-level MFU gauge per batch: attributed train FLOPs over
        measured batch time, against the device peak when one is known
        (telemetry/mfu.py). Achieved-FLOP/s records even without a peak
        (CPU runs still get a throughput-in-FLOPs signal)."""
        prof = getattr(self, "_mfu_profile", None)
        if not prof or not dur_us:
            return
        flops, peak = prof
        secs = dur_us / 1e6
        _telemetry.gauge("mfu.achieved_flops_per_sec").set(flops / secs)
        if peak:
            _telemetry.gauge("mfu.model").set((flops / secs) / peak)

    def _note_batch(self, epoch, nbatch, dur_us, batch_size):
        """Per-logical-batch telemetry shared by both fit loops."""
        if _telemetry.enabled():
            _telemetry.counter("module.fit.batches").inc()
            _telemetry.record_event(
                "batch_end", epoch=epoch, nbatch=nbatch,
                duration_us=dur_us, batch_size=batch_size)
            self._note_mfu(dur_us)
        else:
            _telemetry.flightrec.note(
                "module.fit.batch", epoch=epoch, nbatch=nbatch,
                dur_us=dur_us, batch_size=batch_size)

    def _health_tick(self, epoch, nbatch, steps=1, flush=False):
        """Batch/window-boundary hook of both fit loops: drain the
        in-program health stats (armed runs only) into the process
        HealthMonitor and run the triage ladder on any rule firings.

        Stats drain only once the device reports them finished
        (take_health's readiness gate — an eager read would serialize
        the host behind in-flight windows), so a window's observations
        may arrive several boundaries late, each carrying the cursor of
        the batches that produced it. The escalation cursor stays
        ``(epoch, nbatch + steps)`` — the batches behind it all ran, so
        a resume from an emergency commit is always safe. ``flush``
        drains the whole backlog — the epoch-end call, where the loop
        syncs anyway."""
        hp = _telemetry.health
        eg = getattr(self, "_exec_group", None)
        if eg is None or not hp.armed():
            return
        take = getattr(eg, "take_health", None)
        if take is None:
            return
        stats_list = take(cursor=(epoch, nbatch), flush=flush)
        if not stats_list:
            return
        for stats, ep, nb in stats_list:
            for f in hp.observe(stats, epoch=ep, nbatch=nb):
                hp.escalate(f["rule"], f["policy"], f["message"],
                            module=self, epoch=epoch,
                            nbatch=nbatch + steps)

    # --------------------------------------------- checkpointing / recovery
    def _ckpt_tick(self, epoch, nbatch):
        """Batch-boundary hook of both fit loops: checkpoint cadence +
        the safe point to act on a dead-peer flag. ``nbatch`` is the
        batch that just retired, so the saved cursor is
        ``(epoch, nbatch + 1)`` — the next batch a resume runs."""
        mgr = getattr(self, "_ckpt_manager", None)
        if mgr is not None:
            mgr.tick(self, epoch, nbatch + 1)
        dead = getattr(self, "_dead_nodes_pending", None)
        if dead:
            from ..checkpoint import DeadWorkerError
            self._dead_handled = True   # the wedged watchdog stands down
            if mgr is not None:
                # boundary detection: state is consistent — cut an
                # emergency checkpoint before abandoning the job so
                # resume loses zero batches
                try:
                    mgr.save(self, epoch, nbatch + 1, block=True)
                except Exception:
                    self.logger.exception(
                        "emergency checkpoint failed; resume will use "
                        "the last committed one")
            raise DeadWorkerError(dead, clean=True)

    def _arm_recovery(self, elastic):
        """Subscribe to the kvstore heartbeat layer's dead-node seam
        (elastic mode): the watcher thread only sets a flag, the
        training thread raises at its next batch boundary. A survivor
        can also be WEDGED — blocked inside a collective the dead peer
        will never join (gloo usually fails fast on the broken
        connection, but a collective already in flight at the death can
        hang) — in which case no batch boundary ever comes. With
        ``MXNET_CKPT_HANG_ACTION=reexec`` a grace watchdog handles that
        terminal state the way an elastic agent would: if the training
        thread hasn't acted on the flag within
        ``MXNET_CKPT_HANG_GRACE`` seconds, the process re-execs itself
        over the survivor cluster directly (resume comes from the last
        COMMITTED checkpoint; the wedged step is abandoned)."""
        import threading
        self._dead_nodes_pending = None
        self._dead_handled = False
        self._ckpt_elastic = bool(elastic)
        if not self._ckpt_elastic:
            return
        kv = getattr(self, "_kvstore", None)
        if kv is None or not hasattr(kv, "on_dead_node") or \
                kv.num_workers <= 1:
            return

        def flag(ranks):
            self._dead_nodes_pending = ranks
            if os.environ.get("MXNET_CKPT_HANG_ACTION", "none") == \
                    "reexec":
                grace = float(os.environ.get("MXNET_CKPT_HANG_GRACE",
                                             "60"))
                threading.Thread(target=self._wedged_watchdog,
                                 args=(ranks, grace), daemon=True,
                                 name="mxnet-wedged-watchdog").start()

        kv.on_dead_node(flag)

    def _wedged_watchdog(self, dead_ranks, grace):
        """Last-resort escape for a survivor stuck inside a broken
        collective: after ``grace`` seconds with the dead-peer flag
        unhandled, assume the training thread is wedged in C++ (no
        Python-level interrupt can reach it) and re-exec this process
        over the survivor cluster. State is dirty by definition —
        resume uses the last committed checkpoint."""
        time.sleep(grace)
        if getattr(self, "_dead_handled", False):
            return                  # the training thread got there
        from ..checkpoint import reexec_survivor
        # benign race by design: _dead_handled is a GIL-atomic bool
        # handshake (training thread sets it at a batch boundary, this
        # watchdog checks after the grace window); the worst overlap is
        # both sides acting, and re-exec is idempotent on a committed
        # checkpoint
        self._dead_handled = True  # mxlint: guarded-by(gil)
        _telemetry.counter("recovery.wedged").inc()
        _telemetry.flightrec.note("recovery.wedged",
                                  ranks=list(dead_ranks),
                                  grace_s=grace)
        self.logger.error(
            "dead worker(s) %s flagged %.0fs ago and the training "
            "thread never reached a batch boundary — assuming it is "
            "wedged in a broken collective; re-execing over the "
            "survivor cluster", list(dead_ranks), grace)
        mgr = getattr(self, "_ckpt_manager", None)
        if mgr is not None:
            try:
                mgr.close()         # land any queued commits first
            except Exception:
                pass
        kv = getattr(self, "_kvstore", None)
        if kv is not None:
            try:
                kv.close(abort=True)
            except Exception:
                pass
        reexec_survivor(dead_ranks)

    def _maybe_dead_worker(self, exc):
        """Convert a mid-batch failure into DeadWorkerError when a peer
        is in fact dead (elastic mode): the survivor's collective fails
        fast on the broken connection, but heartbeat staleness needs a
        horizon — poll the liveness layer briefly before deciding the
        failure was something else."""
        from ..checkpoint import DeadWorkerError
        if isinstance(exc, DeadWorkerError):
            return
        if not getattr(self, "_ckpt_elastic", False):
            return
        kv = getattr(self, "_kvstore", None)
        if kv is None or kv.num_workers <= 1 or \
                not hasattr(kv, "get_dead_nodes"):
            return
        dead = getattr(self, "_dead_nodes_pending", None)
        flagged = bool(dead)
        if not dead:
            horizon = float(os.environ.get("PS_HEARTBEAT_TIMEOUT", "100"))
            patience = float(os.environ.get("MXNET_CKPT_DEAD_PATIENCE",
                                            "") or min(horizon + 5, 30))
            deadline = time.time() + patience
            prev = None
            while time.time() < deadline:
                try:
                    seen = kv.get_dead_nodes()
                except Exception:
                    seen = []
                # require two consecutive agreeing observations: a
                # transient coordination-service blip must not get
                # promoted into a cluster re-form
                if seen and seen == prev:
                    dead = seen
                    break
                prev = seen or None
                time.sleep(0.5)
        if dead:
            self._dead_handled = True   # the wedged watchdog stands down
            if not flagged:
                # the watcher thread counts flag-path detections; this
                # is the collective-failure path it hasn't seen yet
                _telemetry.counter("recovery.events").inc()
            _telemetry.flightrec.note("recovery.dead_worker",
                                      ranks=list(dead), clean=False)
            raise DeadWorkerError(dead, clean=False) from exc

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, steps_per_dispatch=None, zero_stage=None,
            spmd=None, mesh=None, checkpoint=None, resume=None,
            elastic=None, remat=None, health=None):
        """The training loop (reference base_module.py:368-507 contract).

        ``steps_per_dispatch`` (default ``MXNET_STEPS_PER_DISPATCH``,
        else 1) batches K training steps into ONE device dispatch via a
        jitted ``lax.scan`` over the fused step — the Python loop, batch
        load and dict-shuffle then cost 1/K per batch (docs/
        performance.md). Metrics/callbacks still fire per batch.

        ``zero_stage`` (default ``MXNET_ZERO_STAGE``, else 0): 1 selects
        ZeRO stage-1 sharded optimizer updates on a multi-device
        binding — gradients reduce-scatter inside the fused program,
        each device updates its 1/N parameter shard with 1/N of the
        optimizer state, updated params all-gather back
        (docs/performance.md). Numerically identical to stage 0.

        ``spmd`` (default ``MXNET_SPMD``, else off): True binds the
        GSPMD arrangement — one jitted program over the named mesh
        (``mesh``: a ``parallel.MeshConfig``; default ``MXNET_MESH_*``
        env, else a 1-D data axis over the contexts), params sharded per
        ctx_group tags, the gradient all-reduce/reduce-scatter emitted
        by XLA from the sharding specs, kvstore optional (pass
        ``kvstore=None``; a local store is dropped automatically).
        Numerically equivalent to the kvstore path
        (docs/performance.md).

        ``checkpoint`` (default: a manager over ``MXNET_CKPT_DIR`` when
        that env var is set, else off): a
        ``checkpoint.CheckpointManager`` — or a directory string to
        build one — that snapshots full training state asynchronously
        at its ``every_n_batches`` cadence plus every epoch end, into
        versioned atomically-committed checkpoint directories
        (docs/checkpoint.md).

        ``resume`` (default off): True (use ``checkpoint``'s directory)
        or a checkpoint-directory string — restore the newest committed
        checkpoint (params, optimizer state + update counts, rng chain)
        and continue from its cursor: earlier epochs are skipped and
        the cursor epoch fast-forwards past already-trained batches, so
        the resumed run continues bit-for-bit where the killed one
        stopped. Under ``steps_per_dispatch`` K the cursor lies on a
        window boundary (checkpoints are cut between windows).

        ``elastic`` (default ``MXNET_CKPT_ELASTIC``): with a dist
        kvstore, subscribe to the heartbeat layer's dead-node seam and
        raise ``checkpoint.DeadWorkerError`` (after an emergency save
        at the next batch boundary) instead of hanging in a collective
        against a dead peer — the caller re-forms the job over the
        survivors (``checkpoint.reexec_survivor``) and resumes.

        ``remat`` (default ``MXNET_REMAT_POLICY``, else ``"none"``):
        rematerialization policy for the fused/K-step program —
        ``"dots"`` recomputes the elementwise chains between saved
        matmul/conv outputs during backward, ``"all"`` replays the
        whole forward — shrinking the step's saved-residual set so the
        HBM freed by ZeRO and the memory accountant buys the
        next-larger batch bucket (docs/performance.md). The policy
        keys the program cache and the kernel-tier autotune cache, and
        extends donation to the step's eval-only intermediates (rng
        chain, fully-refreshed aux).

        ``health`` (default ``MXNET_TRAIN_HEALTH``): True arms the
        training-health plane — the fused/K-step program computes grad/
        param norms, update-ratio, per-head loss and a non-finite flag
        in-program, a ``telemetry.health.HealthMonitor`` (pass one as
        the value to customize detectors) runs divergence rules over
        them at batch/window boundaries, and firings run the triage
        ladder (``warn``/``snapshot``/``checkpoint``/``raise`` —
        ``MXNET_TRAIN_HEALTH_POLICY``), with emergency commits through
        this fit's checkpoint manager (docs/telemetry.md). Arming keys
        the program cache and pins process-wide, like ``remat``.
        """
        from ..initializer import Uniform
        from ..checkpoint import CheckpointManager, DeadWorkerError
        if num_epoch is None:
            raise ValueError("fit() needs num_epoch")
        if steps_per_dispatch is None:
            steps_per_dispatch = int(
                os.environ.get("MXNET_STEPS_PER_DISPATCH", "1") or 1)
        self._steps_per_dispatch = max(1, int(steps_per_dispatch))
        if zero_stage is not None:
            self._zero_stage = int(zero_stage)
        if spmd is not None:
            self._spmd = bool(spmd)
        if mesh is not None:
            self._mesh_config = mesh
        if remat is not None:
            from .. import remat as _remat_mod
            # pin process-wide so the kernel-tier autotune key sees the
            # same policy token the program-cache key carries
            self._remat = _remat_mod.set_active(remat)
        if health is not None:
            # arm (or install a caller-built monitor into) the training-
            # health plane BEFORE the fused program is built below —
            # arming is part of the program-cache key
            if isinstance(health, _telemetry.health.HealthMonitor):
                _telemetry.health.install(health)
            elif isinstance(health, dict):
                _telemetry.health.configure(armed=True, **health)
            else:
                _telemetry.health.configure(armed=bool(health))

        # checkpointing arrangement: explicit kwarg > MXNET_CKPT_DIR env
        # (the env path only engages on modules with an executor group —
        # full-state capture needs one; an explicit kwarg raises loudly)
        mgr, mgr_owned = None, False
        if checkpoint is None and os.environ.get("MXNET_CKPT_DIR") \
                and hasattr(self, "_exec_group"):
            checkpoint = os.environ["MXNET_CKPT_DIR"]
        if checkpoint is not None:
            if isinstance(checkpoint, CheckpointManager):
                mgr = checkpoint
            else:
                mgr = CheckpointManager(str(checkpoint))
                mgr_owned = True
        self._ckpt_manager = mgr
        if elastic is None:
            elastic = os.environ.get("MXNET_CKPT_ELASTIC", "").lower() \
                in ("1", "true", "yes", "on")

        self._prepare_fit(train_data, initializer or Uniform(0.01),
                          arg_params, aux_params, allow_missing,
                          force_rebind, force_init, kvstore, optimizer,
                          optimizer_params, monitor)
        self._arm_recovery(elastic)

        # exact resume: restore the newest committed checkpoint into the
        # freshly prepared module, then continue from its cursor
        skip_batches = 0
        if resume:
            from ..checkpoint import restore_module
            if resume is True and mgr is None:
                raise ValueError("fit(resume=True) needs a checkpoint "
                                 "manager (checkpoint=... or "
                                 "MXNET_CKPT_DIR)")
            resume_dir = mgr.directory if resume is True else str(resume)
            cursor = restore_module(self, resume_dir)
            if cursor is not None and int(cursor["epoch"]) >= begin_epoch:
                begin_epoch = int(cursor["epoch"])
                skip_batches = int(cursor["nbatch"])

        eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        # scan-capable fit over a prefetching iterator: have the
        # producer thread stack K batches per window (and land them in
        # device memory off-thread on a single-device binding)
        K = self._scan_window_size()
        if hasattr(train_data, "stack_windows"):
            if K > 1:
                ctxs = getattr(self, "_context", None)
                dev = ctxs[0] if ctxs and len(ctxs) == 1 else None
                train_data.stack_windows(K, device=dev)
            elif getattr(train_data, "_stack_k", 1) > 1:
                train_data.stack_windows(1)     # scan unavailable: unstack

        # triage binding: checkpoint-level health/sentinel escalations
        # land their emergency commit through THIS fit's manager
        _telemetry.health.bind_triage(self)
        try:
            self._fit_epochs(train_data, eval_data, eval_metric,
                             validation_metric, epoch_end_callback,
                             batch_end_callback, eval_end_callback,
                             eval_batch_end_callback, begin_epoch,
                             num_epoch, monitor,
                             skip_batches=skip_batches)
            if mgr is not None:
                mgr.wait()          # the last checkpoint must be durable
        except DeadWorkerError:
            raise                   # recovery path: dump written already
        except Exception as exc:
            # a dead peer shows up as a failed collective mid-batch:
            # convert to the recovery signal before post-mortem
            self._maybe_dead_worker(exc)
            # leave a post-mortem on disk: ring timeline + metrics +
            # memory watermarks (telemetry.flightrec crash report)
            _telemetry.flightrec.on_crash(exc, where="module.fit")
            raise
        finally:
            _telemetry.health.release_triage()
            if mgr_owned:
                mgr.close()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, epoch_end_callback,
                    batch_end_callback, eval_end_callback,
                    eval_batch_end_callback, begin_epoch, num_epoch,
                    monitor, skip_batches=0):
        for epoch in range(begin_epoch, num_epoch):
            start = time.time()
            eval_metric.reset()
            skip = skip_batches if epoch == begin_epoch else 0
            with _telemetry.span("module.fit.epoch",
                                 _hist="module.fit.epoch.seconds",
                                 epoch=epoch):
                self._fit_epoch(epoch, train_data, eval_metric,
                                batch_end_callback, monitor, skip=skip)

            name_values = eval_metric.get_name_value()
            for name, val in name_values:
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            time_cost = time.time() - start
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time_cost)
            if _telemetry.enabled():
                _telemetry.record_event(
                    "epoch_end", epoch=epoch, time_cost_s=time_cost,
                    metrics={n: float(v) for n, v in name_values})

            # pull the trained params off-device once per epoch so callbacks
            # (checkpointing) see current values
            arg_now, aux_now = self.get_params()
            self.set_params(arg_now, aux_now)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_now, aux_now)

            mgr = getattr(self, "_ckpt_manager", None)
            if mgr is not None:
                # epoch-boundary checkpoint: cursor = start of the next
                # epoch (async; the writer thread owns the disk work)
                mgr.save(self, epoch + 1, 0)

            if eval_data:
                for name, val in self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch):
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    # ------------------------------------------------------------ evaluation
    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Run inference over ``eval_data`` accumulating ``eval_metric``."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()

        nbatch = 0
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                nbatch -= 1
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                _fire(batch_end_callback,
                      BatchEndParam(epoch=epoch, nbatch=nbatch,
                                    eval_metric=eval_metric,
                                    locals=locals()))
        if score_end_callback:
            _fire(score_end_callback,
                  BatchEndParam(epoch=epoch, nbatch=nbatch + 1,
                                eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Yield (outputs-without-pad, batch index, batch) per batch."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            valid = [o[:o.shape[0] - batch.pad] for o in self.get_outputs()]
            yield valid, nbatch, batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect forward outputs over the iterator.

        With ``merge_batches`` the per-batch output lists are concatenated
        along the batch axis (requires a constant output arity — bucketed
        graphs with varying outputs should pass merge_batches=False).
        """
        per_batch = [outs for outs, _, _ in
                     self.iter_predict(eval_data, num_batch, reset)]
        if not per_batch:
            return per_batch
        if not merge_batches:
            return per_batch
        arity = len(per_batch[0])
        if any(len(outs) != arity for outs in per_batch):
            raise ValueError("output arity varies across batches; "
                             "use merge_batches=False")
        merged = [nd.concatenate([outs[i] for outs in per_batch])
                  for i in range(arity)]
        if arity == 1 and not always_output_list:
            return merged[0]
        return merged

    # ---------------------------------------------------------- param access
    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        payload = {f"arg:{k}": v for k, v in arg_params.items()}
        payload.update({f"aux:{k}": v for k, v in aux_params.items()})
        nd.save(fname, payload)

    def load_params(self, fname):
        arg_params, aux_params = {}, {}
        for key, value in nd.load(fname).items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                arg_params[name] = value
            elif kind == "aux":
                aux_params[name] = value
            else:
                raise ValueError(
                    f"{fname} is not a param file (bad key {key!r})")
        self.set_params(arg_params, aux_params)

    # ------------------------------------------------------ abstract surface
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()
