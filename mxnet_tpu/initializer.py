"""Weight initializers (reference: python/mxnet/initializer.py, 612 LoC).

Same name-pattern dispatch contract as the reference: an Initializer is
called as ``init(name, arr)`` and routes on the parameter name suffix
(weight/bias/gamma/beta/moving_*). Randomness uses the framework's global
functional RNG (mxnet_tpu/random.py).

Registry: SURVEY.md A.6 list — Load, Mixed, Zero, One, Constant, Uniform,
Normal, Orthogonal, Xavier, MSRAPrelu, Bilinear, LSTMBias, FusedRNN.
"""
from __future__ import annotations

import json
import re

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from . import random as _random

__all__ = ["InitDesc", "Initializer", "Load", "Mixed", "Zero", "One",
           "Constant", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "LSTMBias", "register",
           "init_registry"]

init_registry = {}


class InitDesc(str):
    """Name descriptor passed to initializers (reference:
    initializer.py:14-33): a str subclass carrying the variable's attrs
    and the global initializer to fall back to. Plain strings work
    everywhere an InitDesc does (Initializer dispatches on the name)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


def register(klass):
    init_registry[klass.__name__.lower()] = klass
    return klass


class Initializer:
    """Base: route by parameter name. reference: initializer.py:21-120."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, name, arr):
        if not isinstance(name, str):
            raise TypeError("name must be string")
        if not isinstance(arr, NDArray):
            raise TypeError("arr must be NDArray")
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(name, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set(jnp.asarray(weight.reshape(shape)))

    def _init_zero(self, _, arr):
        arr._set(jnp.zeros(arr.shape, arr.dtype))

    def _init_one(self, _, arr):
        arr._set(jnp.ones(arr.shape, arr.dtype))

    def _init_bias(self, _, arr):
        arr._set(jnp.zeros(arr.shape, arr.dtype))

    def _init_gamma(self, _, arr):
        arr._set(jnp.ones(arr.shape, arr.dtype))

    def _init_beta(self, _, arr):
        arr._set(jnp.zeros(arr.shape, arr.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError("must override _init_weight")

    def _init_default(self, name, arr):
        raise ValueError(
            f"Unknown initialization pattern for {name}. Default "
            "initialization is now limited to weight/bias/gamma/beta/"
            "moving_* suffixes.")


@register
class Load:
    """Init from an existing param dict. reference: initializer.py:209."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        self.param = {}
        for nm, arr in param.items():
            self.param[nm.split(":", 1)[-1]] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Parameter {name} shape mismatch {src.shape} vs "
                    f"{arr.shape}")
            arr._set(src.asjax() if isinstance(src, NDArray)
                     else jnp.asarray(src))
        else:
            if self.default_init is None:
                raise MXNetError(f"Cannot init parameter {name}")
            self.default_init(name, arr)


@register
class Mixed:
    """Pattern->initializer dispatch. reference: initializer.py:252."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("pattern/initializer length mismatch")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern; add "
                         "a '.*' catch-all")


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr._set(jnp.zeros(arr.shape, arr.dtype))
    _init_default = _init_weight


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr._set(jnp.ones(arr.shape, arr.dtype))
    _init_default = _init_weight


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr._set(jnp.full(arr.shape, self.value, arr.dtype))
    _init_default = _init_weight


@register
class Uniform(Initializer):
    """U(-scale, scale). reference: initializer.py:352."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr._set(jax.random.uniform(_random.next_key(), arr.shape,
                                    dtype=jnp.float32, minval=-self.scale,
                                    maxval=self.scale).astype(arr.dtype))


@register
class Normal(Initializer):
    """N(0, sigma). reference: initializer.py:385."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr._set((self.sigma * jax.random.normal(
            _random.next_key(), arr.shape, dtype=jnp.float32))
            .astype(arr.dtype))


@register
class Orthogonal(Initializer):
    """reference: initializer.py:418 (Saxe et al.)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        key = _random.next_key()
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), minval=-1.0,
                                     maxval=1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin))
        u, _, v = np.linalg.svd(np.asarray(tmp), full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._set(jnp.asarray(self.scale * q.reshape(arr.shape),
                             dtype=arr.dtype))


@register
class Xavier(Initializer):
    """reference: initializer.py:455."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        key = _random.next_key()
        if self.rnd_type == "uniform":
            val = jax.random.uniform(key, shape, dtype=jnp.float32,
                                     minval=-scale, maxval=scale)
        elif self.rnd_type == "gaussian":
            val = scale * jax.random.normal(key, shape, dtype=jnp.float32)
        else:
            raise ValueError("Unknown random type")
        arr._set(val.astype(arr.dtype))


@register
class MSRAPrelu(Xavier):
    """reference: initializer.py:501 (He init with slope correction)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """reference: initializer.py:522."""

    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init. reference: initializer.py:540."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_bias(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr._set(jnp.asarray(b, dtype=arr.dtype))

    _init_weight = Initializer._init_bias


class FusedRNN(Initializer):
    """Init packed fused-RNN parameter blobs. reference: initializer.py:562."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = init_registry[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, name, arr):
        from .rnn.rnn_cell import FusedRNNCell
        cell = FusedRNNCell(self._num_hidden, self._num_layers,
                            self._mode, self._bidirectional,
                            forget_bias=self._forget_bias)
        args = cell.unpack_weights({"parameters": arr})
        for nm in args:
            desc = nm  # e.g. ..._i2h_weight
            if nm.endswith("bias") and self._forget_bias is not None \
                    and self._mode == "lstm":
                continue  # already set by unpack? no — init below
            self._init(desc, args[nm])
        packed = cell.pack_weights(args)
        arr._set(packed["parameters"].asjax())
