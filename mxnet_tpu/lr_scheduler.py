"""Learning-rate schedules.

Behavioral parity with the reference scheduler API (python/mxnet/
lr_scheduler.py: ``__call__(num_update) -> lr``), re-designed stateless:
each schedule is a closed-form function of the global update count rather
than a stateful while-loop, so the same object gives the same answer for
any query order — which also makes schedules safe to evaluate inside a
jitted train step if lowered as a traced scalar.
"""
from __future__ import annotations

import bisect
import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "WarmupScheduler"]

log = logging.getLogger(__name__)


class LRScheduler:
    """Maps the optimizer's global update count to a learning rate.

    ``base_lr`` is injected by ``Optimizer.set_lr_scheduler`` /
    ``Optimizer.__init__`` exactly like the reference does.
    """

    def __init__(self, base_lr: float = 0.01):
        self.base_lr = base_lr
        self._last_logged = None

    def _rate(self, num_update: int) -> float:
        raise NotImplementedError("subclass must implement _rate()")

    def __call__(self, num_update: int) -> float:
        lr = self._rate(num_update)
        if lr != self._last_logged:
            if self._last_logged is not None:
                log.info("lr schedule: update %d -> lr %.3e", num_update, lr)
            self._last_logged = lr
        return lr


class FactorScheduler(LRScheduler):
    """Multiply lr by ``factor`` once every ``step`` updates.

    Closed form of reference FactorScheduler (lr_scheduler.py:32):
    ``lr(u) = base_lr * factor ** floor((u-1)/step)`` clamped at
    ``stop_factor_lr``.
    """

    def __init__(self, step: int, factor: float = 1.0,
                 stop_factor_lr: float = 1e-8):
        super().__init__()
        if step < 1:
            raise ValueError(f"step must be >= 1, got {step}")
        if factor > 1.0:
            raise ValueError(f"a decay factor > 1 would grow the lr: {factor}")
        self.step = int(step)
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def _rate(self, num_update):
        n_decays = max(0, (int(num_update) - 1) // self.step)
        return max(self.base_lr * self.factor ** n_decays,
                   self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """Multiply lr by ``factor`` as each milestone in ``step`` is passed.

    Closed form of reference MultiFactorScheduler (lr_scheduler.py:74):
    the number of decays at update ``u`` is the number of milestones
    strictly below ``u``.
    """

    def __init__(self, step, factor: float = 1.0):
        super().__init__()
        if not step or any(s < 1 for s in step):
            raise ValueError(f"milestones must be positive ints: {step}")
        if any(b <= a for a, b in zip(step, step[1:])):
            raise ValueError(f"milestones must be strictly increasing: {step}")
        if factor > 1.0:
            raise ValueError(f"a decay factor > 1 would grow the lr: {factor}")
        self.step = list(step)
        self.factor = factor

    def _rate(self, num_update):
        n_decays = bisect.bisect_left(self.step, int(num_update))
        return self.base_lr * self.factor ** n_decays


class PolyScheduler(LRScheduler):
    """Polynomial decay to zero over ``max_update`` steps (power ``pwr``)."""

    def __init__(self, max_update: int, pwr: float = 2.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = int(max_update)
        self.pwr = pwr

    def _rate(self, num_update):
        frac = min(int(num_update), self.max_update) / self.max_update
        return self.base_lr * (1.0 - frac) ** self.pwr


class CosineScheduler(LRScheduler):
    """Cosine decay from base_lr to ``final_lr`` over ``max_update`` steps."""

    def __init__(self, max_update: int, final_lr: float = 0.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = int(max_update)
        self.final_lr = final_lr

    def _rate(self, num_update):
        import math
        frac = min(int(num_update), self.max_update) / self.max_update
        return self.final_lr + 0.5 * (self.base_lr - self.final_lr) * (
            1.0 + math.cos(math.pi * frac))


class WarmupScheduler(LRScheduler):
    """Linear warmup over ``warmup_steps`` wrapped around another schedule."""

    def __init__(self, warmup_steps: int, wrapped: LRScheduler):
        super().__init__(wrapped.base_lr)
        self.warmup_steps = int(warmup_steps)
        self.wrapped = wrapped

    def _rate(self, num_update):
        self.wrapped.base_lr = self.base_lr
        if num_update < self.warmup_steps:
            return self.base_lr * (num_update + 1) / self.warmup_steps
        return self.wrapped._rate(num_update)
