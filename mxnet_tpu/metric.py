"""Evaluation metrics (reference: python/mxnet/metric.py, 490 LoC).

Same accumulate-on-host contract as the reference: ``update(labels, preds)``
takes lists of NDArrays, ``get()`` returns (name, value). The ``asnumpy()``
inside update is the step's only sync point — identical to the reference's
behavior (SURVEY.md §3.1).
"""
from __future__ import annotations

import math

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "Perplexity",
           "MAE", "MSE", "RMSE", "CrossEntropy", "CustomMetric",
           "CompositeEvalMetric", "np", "create"]


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(f"Shape of labels {label_shape} does not match "
                         f"shape of predictions {pred_shape}")


class EvalMetric:
    """Base metric. reference: metric.py:21-85."""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def get(self):
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = [f"{self.name}_{i}" for i in range(self.num)]
        values = [x / y if y != 0 else float("nan")
                  for x, y in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    """reference: metric.py:86."""

    def __init__(self, metrics=None, name="composite"):
        super().__init__(name)
        self.metrics = metrics if metrics is not None else []

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range")

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        results = []
        for metric in self.metrics:
            result = metric.get()
            names.append(result[0])
            results.append(result[1])
        return (names, results)


class Accuracy(EvalMetric):
    """reference: metric.py:132."""

    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred = pred_label.asnumpy() if isinstance(pred_label, NDArray) \
                else _np.asarray(pred_label)
            if pred.ndim > 1 and pred.shape != _np.asarray(
                    label.asnumpy() if isinstance(label, NDArray)
                    else label).shape:
                pred = _np.argmax(pred, axis=1)
            lab = (label.asnumpy() if isinstance(label, NDArray)
                   else _np.asarray(label)).astype("int32")
            pred = pred.astype("int32").reshape(lab.shape)
            self.sum_metric += int((pred.flat == lab.flat).sum())
            self.num_inst += len(pred.flat)


class TopKAccuracy(EvalMetric):
    """reference: metric.py:152."""

    def __init__(self, top_k=1):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred = _np.argsort(pred_label.asnumpy().astype("float32"), axis=1)
            lab = label.asnumpy().astype("int32")
            check_label_shapes(lab, pred)
            num_samples = pred.shape[0]
            num_dims = len(pred.shape)
            if num_dims == 1:
                self.sum_metric += (pred.flat == lab.flat).sum()
            elif num_dims == 2:
                num_classes = pred.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (
                        pred[:, num_classes - 1 - j].flat == lab.flat).sum()
            self.num_inst += num_samples


class F1(EvalMetric):
    """Binary F1. reference: metric.py:183."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = pred.asnumpy()
            label = label.asnumpy().astype("int32")
            pred_label = _np.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(_np.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary "
                                 "classification.")
            true_pos = ((pred_label == 1) & (label == 1)).sum()
            false_pos = ((pred_label == 1) & (label == 0)).sum()
            false_neg = ((pred_label == 0) & (label == 1)).sum()
            precision = true_pos / (true_pos + false_pos) \
                if true_pos + false_pos > 0 else 0.0
            recall = true_pos / (true_pos + false_neg) \
                if true_pos + false_neg > 0 else 0.0
            f1_score = 2 * (precision * recall) / (precision + recall) \
                if precision + recall > 0 else 0.0
            self.sum_metric += f1_score
            self.num_inst += 1


class Perplexity(EvalMetric):
    """reference: metric.py:230."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            lab = label.asnumpy().astype("int32").reshape(-1)
            prob = pred.asnumpy().reshape(-1, pred.shape[-1] if self.axis
                                          in (-1, pred.ndim - 1)
                                          else pred.shape[self.axis])
            picked = prob[_np.arange(lab.shape[0]), lab]
            if self.ignore_label is not None:
                ignore = (lab == self.ignore_label)
                picked = _np.where(ignore, 1.0, picked)
                num -= int(ignore.sum())
            loss -= _np.sum(_np.log(_np.maximum(1e-10, picked)))
            num += lab.shape[0]
        self.sum_metric += float(math.exp(loss / max(num, 1))) * max(num, 1)
        self.num_inst += max(num, 1)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)


class MAE(EvalMetric):
    """reference: metric.py:274."""

    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.shape != label.shape:
                pred = pred.reshape(label.shape)
            self.sum_metric += _np.abs(label - pred).mean()
            self.num_inst += 1


class MSE(EvalMetric):
    """reference: metric.py:293."""

    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.shape != label.shape:
                pred = pred.reshape(label.shape)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


class RMSE(EvalMetric):
    """reference: metric.py:311."""

    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.shape != label.shape:
                pred = pred.reshape(label.shape)
            self.sum_metric += _np.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    """reference: metric.py:329."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), _np.int64(label)]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


class CustomMetric(EvalMetric):
    """Wrap a python feval. reference: metric.py:364."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = f"custom({name})"
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = label.asnumpy()
            pred = pred.asnumpy()
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric. reference: metric.py:405."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Create by name/callable/list. reference: metric.py:430."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, **kwargs))
        return composite_metric
    metrics = {
        "acc": Accuracy, "accuracy": Accuracy, "f1": F1,
        "mae": MAE, "mse": MSE, "rmse": RMSE,
        "ce": CrossEntropy, "cross-entropy": CrossEntropy,
        "top_k_accuracy": TopKAccuracy, "perplexity": Perplexity,
    }
    try:
        return metrics[metric.lower()](**kwargs)
    except Exception:
        raise ValueError(f"Metric must be either callable or in "
                         f"{sorted(metrics)}")
