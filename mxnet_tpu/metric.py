"""Evaluation metrics.

API parity with reference python/mxnet/metric.py — ``update(labels,
preds)`` over lists of NDArrays, ``get() -> (name, value)``, the
``asnumpy()`` inside update being the training step's only host sync —
rebuilt around a name registry and shared label/pred normalization
helpers instead of the reference's per-class plumbing. Regression
metrics share one base class with an ``_error`` hook.
"""
from __future__ import annotations

import math

import numpy as _np

from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "Perplexity",
           "MAE", "MSE", "RMSE", "CrossEntropy", "CustomMetric",
           "CompositeEvalMetric", "np", "create", "check_label_shapes"]

_REGISTRY: dict = {}


def _register(*names):
    def deco(cls):
        for n in names:
            _REGISTRY[n] = cls
        return cls
    return deco


def _host(x):
    """NDArray/array-like -> numpy array on host (the sync point)."""
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def check_label_shapes(labels, preds, shape=0):
    """Raise if the label/pred batch lists (or shapes) disagree."""
    got = (len(labels), len(preds)) if shape == 0 \
        else (labels.shape, preds.shape)
    if got[0] != got[1]:
        raise ValueError(
            f"labels {got[0]} and predictions {got[1]} do not match")


def _each(labels, preds, check=True):
    """Yield (label, pred) numpy pairs for one update call."""
    if check:
        check_label_shapes(labels, preds)
    for label, pred in zip(labels, preds):
        yield _host(label), _host(pred)


def _device_pair(lab, pred):
    """(lab_jax, pred_jax) when both live on the same device — the
    device-side metric fast path (no per-batch host pull); else None."""
    if isinstance(pred, NDArray) and isinstance(lab, NDArray):
        pj, lj = pred.asjax(), lab.asjax()
        if pj.devices() == lj.devices():
            return lj, pj
    return None


class EvalMetric:
    """Base class: a running (sum, count) with named readout.

    ``sum_metric`` / ``num_inst`` keep the reference's attribute names —
    downstream code (and the reference's own tests) poke them directly.
    They are flushing properties: reading either drains any queued
    device-side accumulations first, so direct reads never undercount.
    """

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self._pending = []        # before reset(): subclasses override it
        self.reset()

    def reset(self):
        if self.num is None:
            self._sum_metric, self._num_inst = 0.0, 0
        else:
            self._sum_metric = [0.0] * self.num
            self._num_inst = [0] * self.num
        self._pending = []        # device-lazy (total, count) pairs

    # reference-parity attributes; reads flush queued device scalars
    @property
    def sum_metric(self):
        self._flush()
        return self._sum_metric

    @sum_metric.setter
    def sum_metric(self, value):
        # manual pokes DISCARD queued device batches: flushing here would
        # fold the queued counts into both accumulators and then
        # overwrite only this one — a half-applied state (ADVICE r5).
        # Reference-style code that zeroes both attributes gets a clean
        # slate either way.
        self._pending = []
        self._sum_metric = value

    @property
    def num_inst(self):
        self._flush()
        return self._num_inst

    @num_inst.setter
    def num_inst(self, value):
        self._pending = []        # same discard semantics as sum_metric
        self._num_inst = value

    def _accumulate(self, total, count, index=None):
        if index is None:
            self._sum_metric += total
            self._num_inst += count
        else:
            self._sum_metric[index] += total
            self._num_inst[index] += count

    def _accumulate_device(self, total_dev, count):
        """Accumulate a device-resident scalar WITHOUT synchronizing.

        The reference's metrics are host numpy, so every update is a
        device->host pull — through an accelerator runtime that makes
        the metric the training loop's per-batch sync point (measured:
        2 x ~100 ms round trips per batch on a remote chip). Device-side
        metrics queue the async scalar instead; only reading the metric
        (``get``) synchronizes, once, fetching all queued scalars in a
        single transfer batch.
        """
        assert self.num is None, (
            "_accumulate_device supports single-output metrics only "
            "(multi-output sum_metric is a list; use _accumulate)")
        self._pending.append((total_dev, count))

    def _flush(self):
        if not getattr(self, "_pending", None):
            return
        import jax
        pend, self._pending = self._pending, []
        # one pull for everything queued; counts may themselves be
        # device scalars (e.g. Perplexity's ignore-label keep count)
        for total, count in jax.device_get(pend):
            self._accumulate(float(total), int(count))

    def update(self, labels, preds):
        raise NotImplementedError

    @staticmethod
    def _ratio(total, count):
        return total / count if count else float("nan")

    def get(self):
        self._flush()
        if self.num is None:
            return self.name, self._ratio(self.sum_metric, self.num_inst)
        return ([f"{self.name}_{i}" for i in range(self.num)],
                [self._ratio(s, c)
                 for s, c in zip(self.sum_metric, self.num_inst)])

    def get_name_value(self):
        names, values = self.get()
        if not isinstance(names, list):
            names, values = [names], [values]
        return list(zip(names, values))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    """Fan an update out to several child metrics."""

    def __init__(self, metrics=None, name="composite"):
        super().__init__(name)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        if index >= len(self.metrics):
            return ValueError(f"no metric at index {index}")
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        out = [m.get() for m in self.metrics]
        return [n for n, _ in out], [v for _, v in out]


@_register("acc", "accuracy")
class Accuracy(EvalMetric):
    """Fraction of argmax predictions equal to the integer label."""

    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, pred in zip(labels, preds):
            dp = _device_pair(lab, pred)
            if dp is not None:
                # device-side argmax + compare: no per-batch host sync
                import jax.numpy as jnp
                l, p = dp
                l = l.astype(jnp.int32).ravel()
                if p.ndim > 1 and p.shape != dp[0].shape:
                    p = jnp.argmax(p, axis=-1)
                correct = jnp.sum(p.astype(jnp.int32).ravel() == l)
                self._accumulate_device(correct, int(l.size))
                continue
            lab, pred = _host(lab), _host(pred)
            if pred.ndim > 1 and pred.shape != lab.shape:
                pred = pred.argmax(axis=-1)
            lab = lab.astype(_np.int32).ravel()
            pred = pred.astype(_np.int32).ravel()
            self._accumulate(int((pred == lab).sum()), lab.size)


@_register("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    """Label within the k highest-scoring classes.

    Tie-breaking caveat: both paths select exactly k entries, but on
    inputs with *tied* scores the device path (``jax.lax.top_k``) and
    the host path (``np.argpartition``) may pick different tied members,
    so device/host parity is only guaranteed for tie-free scores
    (softmax probabilities from continuous inputs never tie in
    practice). An all-equal row, e.g. uniform zeros, can therefore count
    as a hit on one path and a miss on the other.
    """

    def __init__(self, top_k=1):
        if top_k <= 1:
            raise ValueError("top_k must exceed 1 (use Accuracy otherwise)")
        super().__init__(f"top_k_accuracy_{top_k}")
        self.top_k = top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, pred in zip(labels, preds):
            dp = _device_pair(lab, pred)
            if dp is not None and dp[1].ndim == 2 \
                    and dp[0].size == dp[1].shape[0]:
                import jax
                import jax.numpy as jnp
                l, p = dp
                k = min(self.top_k, p.shape[1])
                _, top = jax.lax.top_k(p, k)
                li = l.astype(jnp.int32).ravel()   # (N,1) labels too
                hits = jnp.sum(jnp.any(top == li[:, None], axis=1))
                self._accumulate_device(hits, int(li.size))
                continue
            lab, pred = _host(lab), _host(pred)
            lab = lab.astype(_np.int32).ravel()
            if pred.ndim == 1:
                hits = int((pred.astype(_np.int32) == lab).sum())
            else:
                k = min(self.top_k, pred.shape[1])
                top = _np.argpartition(pred, -k, axis=1)[:, -k:]
                hits = int((top == lab[:, None]).any(axis=1).sum())
            self._accumulate(hits, lab.size)


@_register("f1")
class F1(EvalMetric):
    """Binary F1 over argmax predictions, averaged per batch."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        for lab, pred in _each(labels, preds):
            lab = lab.astype(_np.int32).ravel()
            if set(_np.unique(lab)) - {0, 1}:
                raise ValueError("F1 is defined for binary labels {0,1}")
            hat = pred.argmax(axis=-1).ravel()
            tp = int(((hat == 1) & (lab == 1)).sum())
            fp = int(((hat == 1) & (lab == 0)).sum())
            fn = int(((hat == 0) & (lab == 1)).sum())
            denom = 2 * tp + fp + fn
            self._accumulate(2.0 * tp / denom if denom else 0.0, 1)


@_register("perplexity")
class Perplexity(EvalMetric):
    """exp(mean negative log-prob of the target), with an optional
    ignored padding label."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        nll, count = 0.0, 0
        for lab_in, prob_in in zip(labels, preds):
            dp = _device_pair(lab_in, prob_in)
            # same size guard as CrossEntropy: a mismatched gather would
            # clamp silently on device; fall to the loud host path
            if dp is not None and \
                    dp[0].size == dp[1].size // dp[1].shape[self.axis]:
                import jax.numpy as jnp
                l, p = dp
                li = l.astype(jnp.int32).ravel()
                ncls = p.shape[self.axis]
                p2 = jnp.moveaxis(p, self.axis, -1).reshape(-1, ncls)
                p_t = p2[jnp.arange(li.shape[0]), li]
                if self.ignore_label is not None:
                    keep = li != self.ignore_label
                    p_t = jnp.where(keep, p_t, 1.0)
                    cnt = jnp.sum(keep)          # device count: flushed
                else:                             # with the total
                    cnt = li.shape[0]
                self._accumulate_device(
                    -jnp.sum(jnp.log(jnp.maximum(p_t, 1e-10))), cnt)
                continue
            lab, prob = _host(lab_in), _host(prob_in)
            lab = lab.astype(_np.int64).ravel()
            ncls = prob.shape[self.axis]
            prob = _np.moveaxis(prob, self.axis, -1).reshape(-1, ncls)
            p_target = prob[_np.arange(lab.size), lab]
            if self.ignore_label is not None:
                keep = lab != self.ignore_label
                p_target = _np.where(keep, p_target, 1.0)
                count += int(keep.sum())
            else:
                count += lab.size
            nll -= float(_np.log(_np.maximum(p_target, 1e-10)).sum())
        # Accumulate raw nll/count so get() returns exp(total_nll/total
        # count) — averaging per-batch perplexities would be biased high
        # (Jensen; reference metric.py Perplexity.get). A fully-ignored
        # batch contributes nothing.
        self._accumulate(nll, count)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


class _RegressionMetric(EvalMetric):
    """Shared shell for elementwise-error metrics (one hook to fill in;
    ``_error`` must be written in array operators + the ``_xp`` module
    handle so the same body runs on numpy (host) and jnp (device))."""

    def _error(self, xp, lab, pred):
        raise NotImplementedError

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, pred in zip(labels, preds):
            dp = _device_pair(lab, pred)
            if dp is not None:
                import jax.numpy as jnp
                l, p = dp
                if l.ndim == 1:
                    l = l[:, None]
                if p.shape != l.shape:
                    p = p.reshape(l.shape)
                self._accumulate_device(self._error(jnp, l, p), 1)
                continue
            lab, pred = _host(lab), _host(pred)
            if lab.ndim == 1:
                lab = lab[:, None]
            if pred.shape != lab.shape:
                pred = pred.reshape(lab.shape)
            self._accumulate(float(self._error(_np, lab, pred)), 1)


@_register("mae")
class MAE(_RegressionMetric):
    def __init__(self):
        super().__init__("mae")

    def _error(self, xp, lab, pred):
        return xp.abs(lab - pred).mean()


@_register("mse")
class MSE(_RegressionMetric):
    def __init__(self):
        super().__init__("mse")

    def _error(self, xp, lab, pred):
        return ((lab - pred) ** 2).mean()


@_register("rmse")
class RMSE(_RegressionMetric):
    def __init__(self):
        super().__init__("rmse")

    def _error(self, xp, lab, pred):
        return xp.sqrt(((lab - pred) ** 2).mean())


@_register("ce", "cross-entropy")
class CrossEntropy(EvalMetric):
    """Mean -log p(target) given per-class probability rows."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, prob in zip(labels, preds):
            dp = _device_pair(lab, prob)
            if dp is not None and dp[1].ndim == 2 \
                    and dp[0].size == dp[1].shape[0]:
                # NOTE: like every XLA gather, out-of-range label values
                # clamp instead of raising — run the host path (numpy
                # inputs) to surface label-range bugs loudly
                import jax.numpy as jnp
                l, p = dp
                li = l.astype(jnp.int32).ravel()
                p_t = p[jnp.arange(li.shape[0]), li]
                self._accumulate_device(-jnp.sum(jnp.log(p_t + self.eps)),
                                        int(li.shape[0]))
                continue
            lab, prob = _host(lab), _host(prob)
            lab = lab.astype(_np.int64).ravel()
            assert lab.shape[0] == prob.shape[0]
            p_target = prob[_np.arange(lab.size), lab]
            self._accumulate(float(-_np.log(p_target + self.eps).sum()),
                             lab.size)


class CustomMetric(EvalMetric):
    """Adapt a python ``feval(label, pred)`` into the metric protocol."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if "<" in name:  # lambdas
                name = f"custom({name})"
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for lab, pred in _each(labels, preds,
                               check=not self._allow_extra_outputs):
            res = self._feval(lab, pred)
            if isinstance(res, tuple):
                self._accumulate(*res)
            else:
                self._accumulate(res, 1)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a bare numpy function as a metric."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Resolve a metric from a name, callable, instance, or list."""
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for m in metric:
            out.add(create(m, **kwargs))
        return out
    try:
        return _REGISTRY[metric.lower()](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown metric {metric!r}; registered: {sorted(_REGISTRY)}")
