"""Multiprocess RecordIO image pipeline (parent side).

The reference's ImageRecordIter throughput comes from a C++ pipeline:
OMP-parallel RecordIO parse + OpenCV decode + augment feeding batch
buffers, with a prefetcher thread on top (reference:
src/io/iter_image_recordio_2.cc:28-595, iter_prefetcher.h:129). The
Python-thread pool in image.py caps out around a few hundred img/s/core
because augmentation fights the GIL.

This module is the scalable path: N worker *processes* (see
_decode_worker.py — self-contained, never imports JAX), each owning its
own file handle on the ``.rec`` pack. The parent scans the pack once
for record frame offsets (header-only seek walk, no decode), then per
batch sends each worker a shard of offsets; workers decode+augment into
shared-memory staging slots and the parent assembles a batch with one
memcpy per shard. Two slots per worker double-buffer, so batch k+1 is
decoding across all cores while the training step consumes batch k.
Decode throughput scales with cores — the design target is the
reference bar of >=1000 img/s/host (benchmarks/io_bench.py records the
measured number per box).

``ImageRecordIter`` (image.py) routes here automatically when its
augmentation is the param-driven CreateAugmenter set; closure-based
custom aug lists keep the thread-pool path. ``MXNET_DECODE_WORKERS``
overrides the worker count (0 disables the multiprocess path).
"""
from __future__ import annotations

import collections
import json
import os
import struct
import subprocess
import sys
import tempfile
from multiprocessing import shared_memory

import numpy as np

from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter
from .ndarray import array

__all__ = ["scan_record_offsets", "MPImageRecordIter"]

_K_MAGIC = 0xced7230a


def scan_record_offsets(rec_path):
    """Walk the pack's frame headers and return every record's
    frame-start offset (no payload reads — this is an O(n_records) seek
    loop, the indexless analog of the reference's .idx sidecar)."""
    offsets = []
    size = os.path.getsize(rec_path)
    with open(rec_path, "rb") as f:
        pos = 0
        while pos + 8 <= size:
            f.seek(pos)
            magic, lrec = struct.unpack("<II", f.read(8))
            if magic != _K_MAGIC:
                raise MXNetError(f"bad RecordIO magic at {pos}")
            length = lrec & ((1 << 29) - 1)
            offsets.append(pos)
            pos += 8 + length + ((4 - length % 4) % 4)
    return offsets


def _load_idx_offsets(idx_path):
    offsets = []
    with open(idx_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) == 2:
                offsets.append(int(parts[1]))
    return offsets


class MPImageRecordIter(DataIter):
    """RecordIO iterator with multiprocess decode into shared memory."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_width=1, shuffle=False,
                 part_index=0, num_parts=1, aug_params=None,
                 num_workers=None, seed=0, data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self._aug = dict(aug_params or {})
        self._seed = seed
        self._epoch = 0

        if path_imgidx and os.path.exists(path_imgidx):
            offsets = _load_idx_offsets(path_imgidx)
        else:
            offsets = scan_record_offsets(path_imgrec)
        if num_parts > 1:
            n = len(offsets) // num_parts
            offsets = offsets[part_index * n:(part_index + 1) * n]
        if not offsets:
            raise MXNetError(f"no records in {path_imgrec}")
        self._offsets = np.asarray(offsets, dtype=np.int64)
        self._shuffle = shuffle

        if num_workers is None:
            num_workers = int(os.environ.get(
                "MXNET_DECODE_WORKERS", min(os.cpu_count() or 1, 8)))
        self._W = max(1, min(num_workers, batch_size))
        self._Q = 2                       # slots per worker (double buffer)
        self._slot_imgs = -(-batch_size // self._W)

        c, h, w = self.data_shape
        self._img_floats = c * h * w
        self._slot_floats = self._slot_imgs * (self._img_floats
                                               + label_width)
        n_slots = self._W * self._Q
        self._shm = shared_memory.SharedMemory(
            create=True, size=n_slots * self._slot_floats * 4)
        self._buf = np.ndarray((n_slots * self._slot_floats,),
                               dtype=np.float32, buffer=self._shm.buf)

        worker_py = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "_decode_worker.py")
        self._procs, self._cfg_files = [], []
        for wi in range(self._W):
            cfg = {"rec_path": path_imgrec, "shm_name": self._shm.name,
                   "n_slots": n_slots, "slot_imgs": self._slot_imgs,
                   "data_shape": list(self.data_shape),
                   "label_width": label_width, "aug": self._aug,
                   "seed": seed * 1000003 + wi}
            cf = tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False)
            json.dump(cfg, cf)
            cf.close()
            self._cfg_files.append(cf.name)
            # keep stderr in a file so a dead worker is diagnosable
            ef = tempfile.NamedTemporaryFile(
                "w", suffix=".log", delete=False)
            self._cfg_files.append(ef.name)
            self._err_files = getattr(self, "_err_files", [])
            self._err_files.append(ef.name)
            self._procs.append(subprocess.Popen(
                [sys.executable, worker_py, cf.name],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=ef, text=True))
            ef.close()
        self._inflight = []               # [(pad, [(worker, slot, n)])]
        # per-worker FIFO of slots awaiting a reply: every reply is
        # matched against the slot it was dispatched for, and error/reset
        # paths drain each stream exactly — otherwise a partially-read
        # batch would desynchronize replies from slots and the parent
        # could copy a slot the worker hasn't confirmed writing
        self._pending = [collections.deque() for _ in range(self._W)]
        self._cursor = 0
        self._order = None
        self.reset()

    # ------------------------------------------------------------- protocol
    def _queue_batch(self, outbox):
        """Stage one batch's offset shards as per-worker orders."""
        start = self._cursor
        idxs = self._order[start:start + self.batch_size]
        if len(idxs) == 0:
            return False
        self._cursor += len(idxs)
        pad = self.batch_size - len(idxs)
        offs = self._offsets[idxs]
        shards = []
        base_slot = (self._seq % self._Q)
        self._seq += 1
        per = self._slot_imgs
        for wi in range(self._W):
            shard = offs[wi * per:(wi + 1) * per]
            if len(shard) == 0:
                break
            slot = wi * self._Q + base_slot
            outbox[wi].append({"slot": slot,
                               "items": [int(o) for o in shard]})
            self._pending[wi].append(slot)
            shards.append((wi, slot, len(shard)))
        self._inflight.append((pad, shards))
        return True

    def _dispatch_batches(self, n):
        """Dispatch up to n batches' decode work, chunked into at most
        ONE stdin write per worker — the json-encode + pipe-syscall cost
        is paid per chunk, not per batch (the priming path covers all Q
        double-buffer slots in a single message per worker)."""
        outbox = [[] for _ in range(self._W)]
        count = 0
        for _ in range(n):
            if not self._queue_batch(outbox):
                break
            count += 1
        for wi, orders in enumerate(outbox):
            if not orders:
                continue
            msg = orders[0] if len(orders) == 1 else {"orders": orders}
            try:
                self._procs[wi].stdin.write(json.dumps(msg) + "\n")
                self._procs[wi].stdin.flush()
            except (BrokenPipeError, OSError):
                raise MXNetError(
                    f"decode worker {wi} died "
                    f"(rc={self._procs[wi].poll()}): "
                    f"{self._worker_stderr(wi)}")
        return count

    def _collect_batch(self):
        if not self._inflight:
            raise StopIteration
        pad, shards = self._inflight.pop(0)
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        labels = np.zeros((self.batch_size, self.label_width),
                          dtype=np.float32)
        row = 0
        for wi, slot, n in shards:
            rep = self._read_reply(wi)
            if rep.get("slot") != slot:
                raise MXNetError(
                    f"decode worker {wi} reply for slot "
                    f"{rep.get('slot')} but slot {slot} expected — "
                    "parent/worker streams desynchronized")
            if "error" in rep:
                raise MXNetError(f"decode worker {wi}: {rep['error']}")
            base = slot * self._slot_floats
            imgs = self._buf[base:base + self._slot_imgs
                             * self._img_floats].reshape(
                self._slot_imgs, c, h, w)
            labs = self._buf[base + self._slot_imgs * self._img_floats:
                             base + self._slot_floats].reshape(
                self._slot_imgs, self.label_width)
            data[row:row + n] = imgs[:n]
            labels[row:row + n] = labs[:n]
            row += n
        return data, labels, pad

    def _read_reply(self, wi):
        """Read one reply line from worker wi and retire its oldest
        pending slot; the caller validates the echoed slot id."""
        line = self._procs[wi].stdout.readline()
        if not line:
            raise MXNetError(
                f"decode worker {wi} died (rc="
                f"{self._procs[wi].poll()}): "
                f"{self._worker_stderr(wi)}")
        if self._pending[wi]:
            self._pending[wi].popleft()
        return json.loads(line)

    def _worker_stderr(self, wi, tail=500):
        try:
            with open(self._err_files[wi]) as f:
                txt = f.read()
            return txt[-tail:] if txt else "(no stderr)"
        except Exception:
            return "(stderr unavailable)"

    # ------------------------------------------------------------ DataIter
    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        # drain every outstanding reply (not just whole in-flight
        # batches: an error may have left a batch partially read) so
        # slots are quiescent before reordering
        for wi in range(self._W):
            while self._pending[wi]:
                self._read_reply(wi)
        self._inflight.clear()
        n = len(self._offsets)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            self._order = rng.permutation(n)
        else:
            self._order = np.arange(n)
        self._epoch += 1
        self._cursor = 0
        self._seq = 0
        self._dispatch_batches(self._Q)   # prime: one chunked message
                                          # per worker covers all slots

    def next(self):
        data, labels, pad = self._collect_batch()
        self._dispatch_batches(1)
        lab = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch([array(data)], [array(lab)], pad=pad)

    def close(self):
        for p in self._procs:
            try:
                p.stdin.write('{"cmd": "quit"}\n')
                p.stdin.flush()
                p.stdin.close()
            except Exception:
                pass
            try:
                p.wait(timeout=5)
            except Exception:
                p.kill()
        self._procs = []
        try:
            self._buf = None
            self._shm.close()
            self._shm.unlink()
        except Exception:
            pass
        for cf in self._cfg_files:
            try:
                os.unlink(cf)
            except (OSError, AttributeError):
                pass              # AttributeError: interpreter shutdown
        self._cfg_files = []

    def __del__(self):
        if getattr(self, "_procs", None):
            self.close()
