"""Scheduler time source: real monotonic clock or a scripted fake.

Every deadline/flush decision in the serving scheduler reads time
through this one seam, so the tier-1 tests can prove deadline-aware
flush semantics with scripted arrivals and zero wall-clock sleeps
(``FakeClock`` + ``InferenceServer.pump()``), while production uses
``time.monotonic``. The fake clock never blocks: ``sleep`` advances
virtual time instantly, which also makes warmup timing measure 0 s —
the deterministic exec-time estimate the scheduler tests rely on.
"""
from __future__ import annotations

import time

__all__ = ["MonotonicClock", "FakeClock"]


class MonotonicClock:
    """Real time: ``time.monotonic`` seconds."""

    def now(self):
        return time.monotonic()

    def sleep(self, seconds):
        if seconds > 0:
            time.sleep(seconds)


class FakeClock:
    """Virtual time under test control.

    ``advance``/``sleep`` move time forward instantly; nothing blocks.
    Use with ``InferenceServer.pump()`` (no dispatch thread): the
    dispatch thread's condition-variable waits are real-time and would
    spin against a clock that only moves when the test says so.
    """

    def __init__(self, start=0.0):
        self._now = float(start)

    def now(self):
        return self._now

    def advance(self, seconds):
        if seconds < 0:
            raise ValueError("time only moves forward")
        self._now += float(seconds)
        return self._now

    def sleep(self, seconds):
        self.advance(max(0.0, seconds))
