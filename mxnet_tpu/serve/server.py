"""InferenceServer: the in-process continuous-batching front end.

``serve(model).submit({"data": x})`` is the whole client API: submit
returns a thread-safe ``ResponseHandle`` (sync ``result()``, async
``done()``/``add_done_callback``) and the server's dispatch thread
drives admission-queue -> dynamic-batch -> pre-compiled bucket program
-> per-request slices. No sockets: the front end is in-process so
tier-1 tests exercise the full scheduler/batcher/registry vertical
hermetically; a network listener is a thin adapter over ``submit``.

Two drive modes:

* ``start()`` — a dispatch thread loops decide/wait/dispatch against
  the real clock (production and the e2e/soak tests);
* ``pump()`` — one explicit scheduling step per call against any clock
  (the deterministic tier-1 path: ``FakeClock`` + scripted arrivals,
  no wall-clock sleeps).

Telemetry (always on — these metrics ARE the serving product surface,
exported by ``telemetry.prometheus`` and rendered by tools/diagnose.py):

====================================  ======  ==========================
``serve.request.latency.seconds``     hist    admission -> completion,
                                              per model (p50/p99 source)
``serve.batch.exec.seconds``          hist    bucket program execution
``serve.queue.depth``                 gauge   per model + global
``serve.batch.occupancy``             gauge   rows/bucket, last dispatch
``serve.padding.waste``               gauge   cumulative padded-row
                                              fraction, per model
``serve.requests|responses|
  dispatches|rejected|errors``        ctr     per model
``serve.rows|padded_rows``            ctr     occupancy/waste numerators
``serve.deadline.miss``               ctr     completed past deadline
``serve.program_cache.
  compiles_since_warmup``             gauge   MUST stay 0 in steady
                                              state (acceptance gate)
====================================  ======  ==========================

plus one flight-ring record per dispatch (``serve.dispatch``) so a
crash report shows the recent serving timeline.
"""
from __future__ import annotations

import logging
import os
import threading

import numpy as np

from .. import faults as _faults
from .. import program_cache as _progcache
from .. import telemetry as _telemetry
from ..telemetry import trace as _trace
from ..base import MXNetError
from ..faults import CircuitOpenError
from .batching import Request, ShedError, pad_rows, slice_rows
from .clock import MonotonicClock
from .engine import BucketEngine, PredictorEngine
from .registry import ModelRegistry

__all__ = ["InferenceServer", "serve"]

log = logging.getLogger(__name__)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class InferenceServer:
    """Continuous-batching server over a multi-tenant model registry.

    Degradation policy (docs/faults.md): a per-model circuit breaker
    (``breaker_threshold`` consecutive dispatch failures open it,
    half-open probe after ``breaker_cooldown_ms``) rejects admission
    fast while open, and when queue depth crosses
    ``shed_watermark`` (fraction of ``max_queue``, or an absolute
    count when >= 1) admission first *sheds* already-doomed queued
    requests — those that cannot meet their deadline even if dispatched
    immediately — before deciding; a full queue rejects with a
    ``retry_after_ms`` backpressure hint derived from the exec-time EMA
    and queue depth.
    """

    def __init__(self, clock=None, max_queue=None, default_deadline_ms=None,
                 logger=None, breaker_threshold=None,
                 breaker_cooldown_ms=None, shed_watermark=None):
        self._clock = clock if clock is not None else MonotonicClock()
        self._max_queue = max_queue if max_queue is not None else \
            _env_int("MXNET_SERVE_MAX_QUEUE", 1024)
        self._default_deadline_s = (
            default_deadline_ms if default_deadline_ms is not None
            else _env_int("MXNET_SERVE_DEADLINE_MS", 100)) / 1000.0
        self.logger = logger or log
        threshold = breaker_threshold if breaker_threshold is not None \
            else _env_int("MXNET_SERVE_BREAKER_THRESHOLD", 5)
        cooldown_s = (breaker_cooldown_ms if breaker_cooldown_ms
                      is not None else
                      _env_int("MXNET_SERVE_BREAKER_COOLDOWN_MS",
                               1000)) / 1000.0
        watermark = shed_watermark if shed_watermark is not None else \
            _env_float("MXNET_SERVE_SHED_WATERMARK", 0.75)
        self._shed_depth = int(watermark) if watermark >= 1 else \
            max(1, int(watermark * self._max_queue))
        self._registry = ModelRegistry(self._max_queue,
                                       breaker_threshold=threshold,
                                       breaker_cooldown_s=cooldown_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._thread = None
        self._running = False
        self._warm_mark = None
        self._slowest = {}      # model -> (trace_id, latency_s)

    # ------------------------------------------------------------- registry
    def register(self, name, model=None, symbol=None, arg_params=None,
                 aux_params=None, data_shapes=None, label_names=None,
                 ladder=None, context=None, compute_dtype=None,
                 predictor=None):
        """Add a model and warm its bucket ladder (compile + pin every
        rung) so steady-state serving never compiles.

        Sources, one of: ``model`` (a bound+initialized Module — symbol,
        params, per-row input shapes, context and compute dtype are
        extracted), ``predictor`` (a ``.mxp`` path or Predictor served
        directly at its exported batch size), or explicit ``symbol`` +
        ``arg_params``/``aux_params`` + ``data_shapes`` (dict input name
        -> per-ROW shape, no batch dim).
        """
        if predictor is not None:
            engine = PredictorEngine(name, predictor, ladder=ladder)
        else:
            if model is not None:
                if not (model.binded and model.params_initialized):
                    raise MXNetError(
                        f"register({name!r}): the Module must be bound "
                        "with initialized params")
                symbol = model._symbol
                arg_params, aux_params = model.get_params()
                data_shapes = {d.name: tuple(d.shape)[1:]
                               for d in model.data_shapes}
                label_names = label_names or list(model._label_names)
                context = context or model._context[0]
                compute_dtype = compute_dtype or model._compute_dtype
            if symbol is None or data_shapes is None:
                raise MXNetError(
                    f"register({name!r}) needs model=, predictor=, or "
                    "symbol= + params + data_shapes")
            # MXNET_SERVE_QUANTIZE=int8|fp8 defaults every symbol-
            # sourced registration onto the quantized ladder (explicit
            # compute_dtype= wins)
            if compute_dtype is None:
                import os as _os
                compute_dtype = _os.environ.get(
                    "MXNET_SERVE_QUANTIZE") or None
            engine = BucketEngine(
                name, symbol, arg_params or {}, aux_params or {},
                data_shapes, label_names=label_names or ("softmax_label",),
                ladder=ladder, context=context,
                compute_dtype=compute_dtype, logger=self.logger)

        with _telemetry.span("serve.warmup", model=name):
            est = engine.warmup(self._clock)
        self.logger.info(
            "serve: model %r warmed — ladder %s, %d compiles, exec est %s",
            name, engine.ladder.sizes, engine.warmup_compiles,
            {b: f"{s * 1e3:.2f}ms" for b, s in est.items()})
        self._registry.add(engine)
        # benign race: a single int reference swapped atomically under
        # the GIL; the dispatch-thread reader only subtracts it from a
        # monotone counter for a gauge, so a stale read skews one
        # scrape, never control flow
        self._warm_mark = _progcache.compile_count()  # mxlint: guarded-by(gil)
        # the serving gauges exist from registration (scrapes before the
        # first request see zeros, not absent series)
        _telemetry.gauge("serve.queue.depth", model=name).set(0)
        _telemetry.gauge("serve.queue.depth").set(self._depth_total())
        _telemetry.gauge(
            "serve.program_cache.compiles_since_warmup").set(0)
        _telemetry.flightrec.note(
            "serve.register", model=name, ladder=list(engine.ladder),
            warmup_compiles=engine.warmup_compiles)
        return engine

    def unregister(self, name):
        """Remove a model, failing its queued requests."""
        entry = self._registry.remove(name)
        entry.queue.fail_all(
            MXNetError(f"model {name!r} unregistered"),
            now=self._clock.now())
        for key in entry.engine.program_keys():
            _progcache.unpin(key)

    @property
    def models(self):
        return self._registry.names()

    def engine(self, name=None):
        return self._registry.engine(name or self._registry.sole_name())

    # ------------------------------------------------------------ admission
    def submit(self, inputs, model=None, deadline_ms=None, trace=None):
        """Admit one request; returns its ``ResponseHandle``.

        ``inputs``: dict input name -> array with a leading row dim
        (1 <= rows <= the model's largest bucket). ``deadline_ms`` is
        relative to now (default ``MXNET_SERVE_DEADLINE_MS``); the
        scheduler flushes the request's batch no later than
        deadline - estimated bucket execution time.

        ``trace``: join an existing ``telemetry.trace.Trace`` (a decode
        session spanning N submits keeps ONE trace; the request's root
        span parents under the session root). Default: a fresh trace
        per request under ``MXNET_TRACE_SAMPLE``, or the engine's
        session trace for a stateful (KV-cache decoder) model.
        """
        name = model or self._registry.sole_name()
        engine = self._registry.engine(name)
        rows, vals = engine.validate(inputs)
        _faults.point("serve.admit", model=name)
        now = self._clock.now()
        deadline_s = (deadline_ms if deadline_ms is not None
                      else self._default_deadline_s * 1000.0) / 1000.0
        tr = trace
        if tr is None:
            tr = getattr(engine, "session_trace", None)
        if tr is None and _trace.sample():
            tr = _trace.new_trace()
        req = Request(name, vals, rows, now, now + deadline_s, trace=tr)
        if tr is not None:
            req.root_sid = _trace.next_span_id()
        with self._cond:
            entry = self._registry.entry(name)
            if not entry.breaker.admit_allowed(now):
                # breaker open: reject fast instead of queueing work
                # onto a model that is structurally failing
                _telemetry.counter("serve.rejected", model=name).inc()
                exc = CircuitOpenError(name,
                                       entry.breaker.retry_after(now))
                if tr is not None:
                    # the rejected request still leaves a trace: a
                    # zero-length root span naming the breaker state,
                    # and the ring record carries the trace id so the
                    # rejection is joinable to the trace after the fact
                    exc.trace_id = tr.trace_id
                    _trace.record(
                        tr, "serve.request", now, now,
                        span_id=req.root_sid,
                        parent=tr.root if tr.session else None,
                        model=name, error="circuit_open",
                        breaker=entry.breaker.state)
                _telemetry.flightrec.note(
                    "serve.breaker.reject", model=name,
                    trace=tr.trace_id if tr is not None else None,
                    retry_after_ms=exc.retry_after_ms)
                raise exc
            if len(entry.queue) >= self._shed_depth:
                self._shed_doomed(entry, now)
            try:
                entry.queue.admit(req)
            except MXNetError as exc:
                _telemetry.counter("serve.rejected", model=name).inc()
                exc.retry_after_ms = self._retry_after_ms(entry)
                if tr is not None:
                    exc.trace_id = tr.trace_id
                raise
            depth = len(entry.queue)
            self._cond.notify_all()
        _telemetry.counter("serve.requests", model=name).inc()
        _telemetry.gauge("serve.queue.depth", model=name).set(depth)
        _telemetry.gauge("serve.queue.depth").set(self._depth_total())
        return req.handle

    def _retry_after_ms(self, entry):
        """Backpressure estimate: time to drain the model's queue at
        the measured exec-time EMA of its largest bucket (>= 1ms so a
        zero estimate — e.g. a FakeClock warmup — still signals
        'later, not now')."""
        ladder = entry.engine.ladder
        est = entry.engine.exec_estimate(ladder.max)
        dispatches = max(1, -(-entry.queue.rows_pending // ladder.max))
        return max(1, int(dispatches * est * 1000))

    def _shed_doomed(self, entry, now):
        """Load-shedding pass (caller holds the lock): complete every
        already-doomed queued request with ``ShedError`` so the slots
        go to requests that can still meet their SLO. ``serve.shed``
        counts these, distinct from ``serve.rejected``."""
        name = entry.engine.name
        ladder = entry.engine.ladder

        def est(rows):
            bucket = ladder.bucket_for(min(rows, ladder.max)) or ladder.max
            return entry.engine.exec_estimate(bucket)

        doomed = entry.queue.shed_doomed(now, est)
        if not doomed:
            return
        retry_after = self._retry_after_ms(entry)
        depth = len(entry.queue)
        _telemetry.counter("serve.shed", model=name).inc(len(doomed))
        _telemetry.flightrec.note(
            "serve.shed", model=name, n=len(doomed),
            retry_after_ms=retry_after,
            # the shed decision is joinable to its victims' traces —
            # and each victim's root span (below) carries the queue
            # state that doomed it
            trace_ids=[r.trace.trace_id for r in doomed[:8]
                       if r.trace is not None])
        for r in doomed:
            err = ShedError(
                f"model {name!r}: request {r.id} shed at queue depth "
                f"watermark — deadline unreachable before dispatch")
            err.retry_after_ms = retry_after
            if r.trace is not None:
                err.trace_id = r.trace.trace_id
                _trace.record(
                    r.trace, "serve.queue.wait", r.arrival, now,
                    parent=r.root_sid)
                _trace.record(
                    r.trace, "serve.request", r.arrival, now,
                    span_id=r.root_sid,
                    parent=r.trace.root if r.trace.session else None,
                    model=name, rows=r.rows, error="shed",
                    queue_depth=depth, shed_depth=self._shed_depth,
                    retry_after_ms=retry_after)
            r.handle._complete(error=err, now=now)

    def _depth_total(self):
        return sum(len(e.queue) for e in self._registry.entries())

    # ------------------------------------------------------------- dispatch
    def _dispatch(self, name):
        """Drain one dynamic batch for ``name`` and run it. Returns the
        number of requests served (0 if the queue emptied under us)."""
        with self._lock:
            entry = self._registry.entry(name)
            if entry is None:
                return 0
            engine = entry.engine
            # the breaker gates every attempt: open = no dispatch,
            # open-past-cooldown = this drain becomes the half-open probe
            if not entry.breaker.acquire(self._clock.now()):
                return 0
            reqs, rows = entry.queue.drain(engine.ladder.max)
            if not reqs:
                entry.breaker.release()     # probe unused, nothing queued
                return 0
            self._registry.note_dispatch(name)
            depth = len(entry.queue)
        bucket = engine.ladder.bucket_for(rows)
        wait_s = self._clock.now() - min(r.arrival for r in reqs)
        traced = [r for r in reqs if r.trace is not None]
        # batched requests share ONE dispatch span id: the span is
        # mirrored into each member's trace under that member's root,
        # so every request reconstructs alone and batch-mates join on
        # the shared id
        shared_sid = _trace.next_span_id() if traced else None

        # the flush break-even must cover the WHOLE dispatch cost the
        # tail request pays, so t0 starts before batch assembly
        t0 = self._clock.now()
        values = {
            nm: pad_rows(
                np.concatenate([r.inputs[nm] for r in reqs], axis=0)
                if len(reqs) > 1 else reqs[0].inputs[nm], bucket)
            for nm in engine.data_names}
        asm_end = self._clock.now()
        try:
            _faults.point("serve.dispatch", model=name, bucket=bucket)
            outs = engine.forward(bucket, values)
            import jax
            for o in outs:
                jax.block_until_ready(o.asjax())
        except Exception as exc:    # fail the whole batch, keep serving
            now = self._clock.now()
            entry.breaker.record_failure(now)
            for r in reqs:
                if r.trace is not None:
                    _trace.record(
                        r.trace, "serve.request", r.arrival, now,
                        span_id=r.root_sid,
                        parent=r.trace.root if r.trace.session else None,
                        model=name, rows=r.rows, bucket=bucket,
                        error=type(exc).__name__)
                r.handle._complete(error=exc, now=now)
            _telemetry.counter("serve.errors", model=name).inc()
            _telemetry.flightrec.note(
                "serve.dispatch.error", model=name,
                bucket=bucket, error=repr(exc),
                breaker=entry.breaker.state,
                trace_ids=[r.trace.trace_id for r in traced[:8]])
            self.logger.exception("serve: dispatch failed for %r", name)
            return len(reqs)
        entry.breaker.record_success(self._clock.now())
        exec_s = self._clock.now() - t0
        engine.note_exec(bucket, exec_s)
        exec_end = self._clock.now()

        now = self._clock.now()
        off = 0
        misses = 0
        lat_hist = _telemetry.histogram("serve.request.latency.seconds",
                                        model=name)
        for r in reqs:
            r.handle._complete(outputs=slice_rows(outs, off, r.rows),
                               bucket=bucket, now=now)
            off += r.rows
            lat_hist.observe(now - r.arrival,
                             exemplar=r.trace.trace_id
                             if r.trace is not None else None)
            if now > r.deadline:
                misses += 1
        resp_end = self._clock.now()
        for r in traced:
            self._record_request_trace(r, name, bucket, len(reqs),
                                       shared_sid, t0, asm_end,
                                       exec_end, resp_end,
                                       missed=resp_end > r.deadline)

        _telemetry.histogram("serve.batch.exec.seconds",
                             model=name).observe(exec_s)
        _telemetry.counter("serve.responses", model=name).inc(len(reqs))
        _telemetry.counter("serve.dispatches", model=name).inc()
        rows_c = _telemetry.counter("serve.rows", model=name).inc(rows)
        pad_c = _telemetry.counter("serve.padded_rows",
                                   model=name).inc(bucket)
        if misses:
            _telemetry.counter("serve.deadline.miss",
                               model=name).inc(misses)
        _telemetry.gauge("serve.batch.occupancy",
                         model=name).set(rows / bucket)
        _telemetry.gauge("serve.padding.waste", model=name).set(
            1.0 - rows_c.value / pad_c.value if pad_c.value else 0.0)
        _telemetry.gauge("serve.queue.depth", model=name).set(depth)
        _telemetry.gauge("serve.queue.depth").set(self._depth_total())
        compiles = engine.compiles_since_warmup()
        if self._warm_mark is not None:
            _telemetry.gauge(
                "serve.program_cache.compiles_since_warmup").set(
                _progcache.compile_count() - self._warm_mark)
        _telemetry.flightrec.note(
            "serve.dispatch", model=name, bucket=bucket, rows=rows,
            n_requests=len(reqs), occupancy=round(rows / bucket, 3),
            wait_us=int(wait_s * 1e6), exec_us=int(exec_s * 1e6),
            deadline_misses=misses, compiles_since_warmup=compiles,
            trace_ids=[r.trace.trace_id for r in traced[:8]])
        return len(reqs)

    def _record_request_trace(self, r, name, bucket, n_requests,
                              shared_sid, t0, asm_end, exec_end,
                              resp_end, missed=False):
        """Record one served request's span tree (telemetry.trace):

        ::

            serve.request                arrival -> respond
            ├─ serve.queue.wait          arrival -> drain
            └─ serve.dispatch (shared)   drain   -> exec done
               ├─ serve.assemble         pad / coalesce
               ├─ serve.exec             bucket program + block
               └─ serve.respond          slice + complete

        The dispatch span id is shared across the batch; its children
        are mirrored per member trace so each tree stands alone. For a
        decode session the request root parents under the session root,
        which is re-recorded (same span id, growing duration) so the
        whole N-step decode stays ONE tree.
        """
        tr = r.trace
        parent = None
        if tr.session:
            if tr.root is None:
                tr.root = _trace.next_span_id()
            if tr.start_s is None:
                tr.start_s = r.arrival
            parent = tr.root
        _trace.record(tr, "serve.queue.wait", r.arrival, t0,
                      parent=r.root_sid)
        _trace.record(tr, "serve.dispatch", t0, exec_end,
                      span_id=shared_sid, parent=r.root_sid,
                      bucket=bucket, n_requests=n_requests, shared=True)
        _trace.record(tr, "serve.assemble", t0, asm_end,
                      parent=shared_sid)
        _trace.record(tr, "serve.exec", asm_end, exec_end,
                      parent=shared_sid)
        _trace.record(tr, "serve.respond", exec_end, resp_end,
                      parent=shared_sid)
        _trace.record(tr, "serve.request", r.arrival, resp_end,
                      span_id=r.root_sid, parent=parent, model=name,
                      rows=r.rows, bucket=bucket,
                      deadline_miss=bool(missed))
        if tr.session:
            _trace.record(tr, "serve.decode.session", tr.start_s,
                          resp_end, span_id=tr.root, model=name)
        # the per-model slowest completed trace (stats() surfaces it);
        # the read-compare-write races the caller-thread stats() reader
        # without the lock
        lat = resp_end - r.arrival
        with self._lock:
            worst = self._slowest.get(name)
            if worst is None or lat > worst[1]:
                self._slowest[name] = (tr.trace_id, lat)

    # ----------------------------------------------------------- drive modes
    def pump(self, max_dispatches=None):
        """Deterministic drive: dispatch every model that is ready at
        the scheduler clock's *now*, without waiting. Returns the number
        of dispatches performed. The explicit alternative to ``start()``
        for FakeClock tests — no thread, no sleeps."""
        done = 0
        while max_dispatches is None or done < max_dispatches:
            with self._lock:
                action, arg = self._registry.next_action(self._clock.now())
            if action != "dispatch":
                break
            self._dispatch(arg)
            done += 1
        return done

    def _loop(self):
        while True:
            with self._cond:
                if not self._running:
                    return
                action, arg = self._registry.next_action(self._clock.now())
                if action == "wait":
                    # bounded by the earliest flush_at; an admission
                    # notify re-evaluates sooner. The condvar waits real
                    # time — production pairs the thread with the real
                    # clock (FakeClock users drive pump() directly).
                    self._cond.wait(timeout=arg)
                    continue
            self._dispatch(arg)

    def start(self):
        """Spawn the dispatch thread (idempotent)."""
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="mxnet-serve-dispatch",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True):
        """Stop the dispatch thread; ``drain`` serves remaining queued
        requests before returning, else they fail with MXNetError."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if drain:
            while any(len(e.queue) for e in self._registry.entries()):
                for e in self._registry.entries():
                    if len(e.queue):
                        self._dispatch(e.engine.name)
        else:
            now = self._clock.now()
            for e in self._registry.entries():
                e.queue.fail_all(MXNetError("server stopped"), now=now)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # --------------------------------------------------------- warm restart
    def checkpoint_to(self, manager, block=True):
        """Persist the registry/ladder configuration (symbols, params,
        shapes, ladders) through a ``CheckpointManager`` so a restarted
        process can rebuild this server with ``serve.restore_server``
        and serve again with zero compiles beyond warmup — the serving
        half of the elastic-recovery story (docs/serving.md).
        ``manager`` is a ``CheckpointManager`` or a directory string.
        Returns the committed seq."""
        from .warm import save_server
        return save_server(self, manager, block=block)

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Snapshot for dashboards/bench: per-model p50/p99 latency,
        occupancy, padding waste, queue depth, counters, exec
        estimates; plus the process compile delta since warmup."""
        models = {}
        for e in self._registry.entries():
            name = e.engine.name

            def c(metric):
                m = _telemetry.get_metric(metric, model=name)
                return m.value if m is not None else 0

            h = _telemetry.get_metric("serve.request.latency.seconds",
                                      model=name)
            rows_v, pad_v = c("serve.rows"), c("serve.padded_rows")
            with self._lock:
                worst = self._slowest.get(name)
            slowest = None if worst is None else {
                "trace": worst[0],
                "latency_ms": round(worst[1] * 1e3, 3)}
            models[name] = {
                "requests": c("serve.requests"),
                "responses": c("serve.responses"),
                "dispatches": c("serve.dispatches"),
                "rejected": c("serve.rejected"),
                "shed": c("serve.shed"),
                "errors": c("serve.errors"),
                "breaker": e.breaker.state,
                "deadline_misses": c("serve.deadline.miss"),
                "queue_depth": len(e.queue),
                "latency_ms": None if h is None or not h.count else {
                    "p50": round((h.quantile(0.50) or 0) * 1e3, 3),
                    "p99": round((h.quantile(0.99) or 0) * 1e3, 3),
                    "mean": round(h.mean * 1e3, 3),
                    "max": round((h.max or 0) * 1e3, 3)},
                # exemplars: concrete traces behind the aggregates — a
                # p99 number links to a request you can reconstruct
                # with telemetry.trace.tree()
                "p99_trace": None if h is None else h.exemplar(0.99),
                "slowest_trace": slowest,
                "batch_occupancy": round(rows_v / pad_v, 4)
                if pad_v else None,
                "padding_waste_pct": round(100 * (1 - rows_v / pad_v), 2)
                if pad_v else None,
                "ladder": e.engine.ladder.sizes,
                "exec_est_ms": {b: round(s * 1e3, 3) for b, s in
                                sorted(e.engine.exec_est.items())},
                "programs_resident": e.engine.programs_resident(),
                "quantized": getattr(e.engine, "quantized", None),
            }
        compiles = None
        if self._warm_mark is not None:
            compiles = _progcache.compile_count() - self._warm_mark
        return {"models": models, "compiles_since_warmup": compiles}


def serve(model, name="default", ladder=None, start=True, clock=None,
          max_queue=None, default_deadline_ms=None, breaker_threshold=None,
          breaker_cooldown_ms=None, shed_watermark=None, **register_kw):
    """One-call front end: ``serve(model).submit({...})``.

    ``model``: a bound+initialized Module, a ``Predictor``, or a path
    to a ``.mxp`` artifact. Builds a single-model ``InferenceServer``,
    warms the ladder, and (by default) starts the dispatch thread; use
    ``start=False`` + ``pump()`` with a FakeClock for deterministic
    scheduling tests.
    """
    from ..predict import Predictor
    server = InferenceServer(clock=clock, max_queue=max_queue,
                             default_deadline_ms=default_deadline_ms,
                             breaker_threshold=breaker_threshold,
                             breaker_cooldown_ms=breaker_cooldown_ms,
                             shed_watermark=shed_watermark)
    if isinstance(model, (str, Predictor)):
        server.register(name, predictor=model, ladder=ladder,
                        **register_kw)
    else:
        server.register(name, model=model, ladder=ladder, **register_kw)
    if start:
        server.start()
    return server
