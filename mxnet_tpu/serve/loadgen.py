"""Open-loop load generation: Poisson arrivals + scripted replays.

Open-loop means arrivals are scheduled from the arrival process alone —
a slow server does NOT slow the generator down (closed-loop generators
hide overload by self-throttling; the req/s-at-p99-SLO number bench.py
reports is only honest open-loop). Two drivers over one summary:

* ``PoissonLoadGen`` — real-clock Poisson process at ``rate`` req/s
  against a started server; the bench ``serve`` row and the
  ``@slow``-marked soak test use it;
* ``run_scripted`` — deterministic replay of explicit arrival times
  against a FakeClock server via ``pump()``: zero wall-clock sleeps,
  exact flush/deadline decisions, the tier-1 scheduler gate.

``summarize`` folds completed handles into the req/s + latency
percentile + SLO-attainment dict both paths (and bench.py) report.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .batching import QueueFullError

__all__ = ["PoissonLoadGen", "run_scripted", "summarize"]


def summarize(handles, elapsed_s, slo_ms=None):
    """Fold handles into the load-test report dict.

    ``elapsed_s``: generator-side wall (or virtual) span the requests
    were offered over — the req/s denominator. ``slo_ms`` adds
    ``p99_within_slo`` (the bench gate: p99 latency <= SLO).
    """
    done = [h for h in handles if h.done() and h.exception() is None]
    lat = sorted(h.latency for h in done if h.latency is not None)
    misses = sum(1 for h in done if h.missed_deadline())

    def pct(q):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(q * len(lat)))] * 1e3, 3)

    out = {
        "offered": len(handles),
        "completed": len(done),
        "errors": sum(1 for h in handles
                      if h.done() and h.exception() is not None),
        "req_per_sec": round(len(done) / elapsed_s, 2) if elapsed_s else
        None,
        "latency_ms": {"p50": pct(0.50), "p90": pct(0.90),
                       "p99": pct(0.99),
                       "mean": round(float(np.mean(lat)) * 1e3, 3)
                       if lat else None},
        "deadline_misses": misses,
    }
    if slo_ms is not None:
        out["slo_ms"] = slo_ms
        out["p99_within_slo"] = (out["latency_ms"]["p99"] is not None
                                 and out["latency_ms"]["p99"] <= slo_ms)
    return out


class PoissonLoadGen:
    """Real-clock open-loop Poisson generator against a started server."""

    def __init__(self, server, make_input, model=None, rate=50.0,
                 n_requests=200, deadline_ms=None, seed=0):
        """``make_input(i, rng)`` -> the inputs dict for request i (vary
        row counts here to exercise mixed shapes); ``rate``: mean
        arrivals/second of the exponential inter-arrival draw."""
        if rate <= 0:
            raise MXNetError("rate must be positive")
        self.server = server
        self.make_input = make_input
        self.model = model
        self.rate = float(rate)
        self.n_requests = int(n_requests)
        self.deadline_ms = deadline_ms
        self.seed = seed

    def run(self, slo_ms=None, result_timeout_s=60.0):
        """Offer the full arrival schedule, wait for completions, and
        return ``summarize(...)`` plus the offered-rate bookkeeping."""
        rng = np.random.RandomState(self.seed)
        gaps = rng.exponential(1.0 / self.rate, size=self.n_requests)
        clock = self.server._clock
        t0 = clock.now()
        handles = []
        next_at = t0
        for i in range(self.n_requests):
            next_at += gaps[i]
            clock.sleep(next_at - clock.now())
            try:
                handles.append(self.server.submit(
                    self.make_input(i, rng), model=self.model,
                    deadline_ms=self.deadline_ms))
            except QueueFullError:
                handles.append(None)   # overload: counted as rejected
        offered_span = clock.now() - t0
        live = [h for h in handles if h is not None]
        for h in live:
            h.result(timeout=result_timeout_s)
        out = summarize(live, clock.now() - t0, slo_ms=slo_ms)
        out["rejected"] = sum(1 for h in handles if h is None)
        out["offered_rate_req_s"] = round(
            self.n_requests / offered_span, 2) if offered_span else None
        return out


def run_scripted(server, arrivals, make_input, model=None,
                 deadline_ms=None, slo_ms=None):
    """Deterministic replay: ``arrivals`` are absolute FakeClock times.

    The server must NOT be started — the script advances the clock to
    each arrival, submits, and ``pump()``s, then advances past the last
    deadline and pumps until drained. Everything (flush instants,
    latencies, percentiles) is exact and repeatable.
    """
    clock = server._clock
    if not hasattr(clock, "advance"):
        raise MXNetError("run_scripted needs a FakeClock-driven server")
    handles = []
    t_start = clock.now()
    for i, t in enumerate(sorted(arrivals)):
        if t > clock.now():
            # walk deadline boundaries between now and the arrival so
            # flushes fire at their exact scheduled instants
            while True:
                with server._lock:
                    action, wait = server._registry.next_action(
                        clock.now())
                if action != "wait" or wait is None or \
                        clock.now() + wait > t:
                    break
                clock.advance(wait)
                server.pump()
            clock.advance(max(0.0, t - clock.now()))
        server.pump()
        handles.append(server.submit(
            make_input(i, np.random.RandomState(i)), model=model,
            deadline_ms=deadline_ms))
        server.pump()
    # drain: advance through remaining flush instants
    while any(len(e.queue) for e in server._registry.entries()):
        with server._lock:
            action, wait = server._registry.next_action(clock.now())
        if action == "wait":
            if wait is None:
                raise MXNetError("scripted drain stuck: queued work "
                                 "with no flush deadline")
            clock.advance(wait)
        server.pump()
    return summarize(handles, clock.now() - t_start, slo_ms=slo_ms)
