"""Serve warm restart: checkpoint the registry, rebuild after re-exec.

The training side survives a kill because ``CheckpointManager`` owns a
versioned, atomically-committed copy of everything a resume needs. This
module closes the ROADMAP-5 remainder ("wiring serve/ warm restarts to
the same manager") by giving ``InferenceServer`` the same property: the
whole registry/ladder configuration — per model, the symbol (JSON), the
trained params (numpy), input shapes/label names, the bucket ladder,
the compute dtype — plus the server's admission/degradation settings,
rides through ``CheckpointManager.save_payload`` as a ``kind="serve"``
payload into the same atomic-commit directories (training and serving
state can share one checkpoint root; readers filter by kind).

After a crash/re-exec, :func:`restore_server` reads the newest
*readable* serve commit (the damage-tolerant fallback walk
``read_committed_payload`` provides), re-registers every model — which
re-runs warmup: compile every rung, pin the programs — and returns a
server that serves again with **zero compiles beyond warmup**: the
acceptance gate ``program_cache.compile_count()`` delta == 0 from the
post-warmup mark, the same contract a first boot makes. Requests that
were accepted-and-acked before the kill already hold their results in
their ``ResponseHandle``; queued-unacked requests fail loudly at
``stop``/death (at-most-once admission — the client retries against
the restarted server).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .engine import BucketEngine, PredictorEngine
from .server import InferenceServer

__all__ = ["save_server", "restore_server", "server_payload"]

log = logging.getLogger(__name__)


def server_payload(server):
    """The serve-state dict one commit persists (numpy/JSON only — the
    writer thread pickles it as-is)."""
    from ..checkpoint.state import FORMAT_VERSION
    models = {}
    for entry in server._registry.entries():
        eng = entry.engine
        name = eng.name
        if isinstance(eng, BucketEngine):
            arg, aux = eng._bm.get_params()
            models[name] = {
                "type": "bucket",
                "symbol": eng._symbol.tojson(),
                "arg_params": {k: v.asnumpy() for k, v in arg.items()},
                "aux_params": {k: v.asnumpy() for k, v in aux.items()},
                "data_shapes": {nm: tuple(s) for nm, s in
                                eng.example_shapes.items()},
                "label_names": list(eng._label_names),
                "ladder": list(eng.ladder.sizes),
                "compute_dtype": eng._compute_dtype,
                # int8 engines persist the ALREADY-quantized symbol +
                # params (compute_dtype is None by then), so restore
                # re-binds without re-quantizing; recorded for audit
                "quantized": getattr(eng, "quantized", None),
            }
        elif isinstance(eng, PredictorEngine) and eng._path is not None:
            models[name] = {"type": "predictor", "path": eng._path}
        else:
            log.warning(
                "serve checkpoint: model %r has no persistable source "
                "(in-memory Predictor without an artifact path); it "
                "will be missing after a warm restart", name)
    return {
        "version": FORMAT_VERSION,
        "kind": "serve",
        "cursor": {"epoch": 0, "nbatch": 0},
        "server": {
            "max_queue": server._max_queue,
            "default_deadline_ms": int(server._default_deadline_s * 1000),
            "shed_depth": server._shed_depth,
        },
        "models": models,
    }


def save_server(server, manager, block=True):
    """Commit the server's registry/config through ``manager`` (a
    ``CheckpointManager`` or a directory string); returns the seq."""
    from ..checkpoint import CheckpointManager
    owned = False
    if not isinstance(manager, CheckpointManager):
        manager = CheckpointManager(str(manager))
        owned = True
    try:
        return manager.save_payload(server_payload(server), block=block)
    finally:
        if owned:
            manager.close()


def restore_server(directory, clock=None, start=False, context=None,
                   **server_kw):
    """Rebuild an ``InferenceServer`` from the newest readable
    ``kind="serve"`` commit in ``directory``.

    Re-registering each model re-runs warmup (compile + pin every
    rung — with ``MXNET_COMPILATION_CACHE_DIR`` set even those compiles
    hit the persistent XLA cache), after which steady-state serving
    compiles nothing: ``compile_count()`` stays at the post-warmup
    mark. ``server_kw`` overrides the persisted server settings;
    ``context`` places the restored models (default: current device).
    """
    from ..checkpoint import read_committed_payload
    from ..ndarray import array
    from ..symbol import load_json

    found = read_committed_payload(directory, kind="serve")
    if found is None:
        raise MXNetError(
            f"no committed serve state under {directory!r} "
            "(was InferenceServer.checkpoint_to ever called?)")
    seq, path, payload = found
    saved = payload.get("server") or {}
    kw = {"max_queue": saved.get("max_queue"),
          "default_deadline_ms": saved.get("default_deadline_ms")}
    kw.update(server_kw)
    server = InferenceServer(clock=clock, **kw)
    for name, rec in (payload.get("models") or {}).items():
        if rec["type"] == "predictor":
            server.register(name, predictor=rec["path"])
            continue
        server.register(
            name,
            symbol=load_json(rec["symbol"]),
            arg_params={k: array(np.asarray(v))
                        for k, v in rec["arg_params"].items()},
            aux_params={k: array(np.asarray(v))
                        for k, v in rec["aux_params"].items()},
            data_shapes=rec["data_shapes"],
            label_names=rec["label_names"] or None,
            ladder=rec["ladder"],
            context=context,
            compute_dtype=rec.get("compute_dtype"))
    log.info("serve: warm-restarted %d model(s) from %s (seq %d)",
             len(payload.get("models") or {}), path, seq)
    if start:
        server.start()
    return server
