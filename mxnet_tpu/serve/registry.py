"""Multi-tenant model registry + deadline-aware fair scheduling.

Several models share one device pool (engines execute serially on the
dispatch thread — one XLA stream, the device is the shared resource);
the registry owns, per model, the engine, the admission queue, and the
scheduling bookkeeping. The pick rule combines the two properties the
ISSUE names:

* **deadline-aware**: a model becomes *ready* when its queued rows fill
  the largest ladder bucket (no batching benefit left in waiting) OR
  when the scheduler clock reaches its ``flush_at`` — the earliest
  queued deadline minus the measured execution estimate for the bucket
  that would serve the queue *right now*. Past ``flush_at``, waiting
  for a larger bucket would blow the SLO of a request a smaller bucket
  can still serve on time (the acceptance property
  tests/test_serve.py::test_deadline_flush_fake_clock pins).
* **fair**: among simultaneously-ready models, least-recently-
  dispatched wins (round-robin under saturation), so one hot tenant
  cannot starve another — every dispatch bumps the model's serial.

``next_action`` is a pure decision function over (queues, clock): it
returns ``("dispatch", model)`` or ``("wait", seconds|None)`` and
mutates nothing, so the deterministic tests drive it directly.
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from ..faults import CircuitBreaker
from .batching import AdmissionQueue

__all__ = ["ModelRegistry"]


class _Entry:
    __slots__ = ("engine", "queue", "breaker", "last_dispatch_seq")

    def __init__(self, engine, max_queue, breaker_threshold,
                 breaker_cooldown_s):
        self.engine = engine
        self.queue = AdmissionQueue(engine.name, max_queue)
        # per-model circuit breaker: consecutive dispatch failures open
        # it, a half-open probe after the cooldown decides recovery
        # (docs/faults.md; state drives both admission and next_action)
        self.breaker = CircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s,
            site=f"serve:{engine.name}", labels={"model": engine.name},
            metric_prefix="serve.breaker")
        self.last_dispatch_seq = 0


class ModelRegistry:
    """name -> (engine, admission queue, breaker, fairness serial)."""

    def __init__(self, max_queue, breaker_threshold=5,
                 breaker_cooldown_s=1.0):
        self._entries = {}
        self._max_queue = max_queue
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._seq = 0
        self._lock = threading.Lock()   # registration only; the server
                                        # lock serializes scheduling

    def add(self, engine):
        with self._lock:
            if engine.name in self._entries:
                raise MXNetError(
                    f"model {engine.name!r} already registered")
            self._entries[engine.name] = _Entry(
                engine, self._max_queue, self._breaker_threshold,
                self._breaker_cooldown_s)
        return engine

    def remove(self, name):
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is None:
            raise MXNetError(f"no model {name!r} registered")
        return entry

    def __contains__(self, name):
        return name in self._entries

    def names(self):
        return list(self._entries)

    def engine(self, name):
        entry = self._entries.get(name)
        if entry is None:
            raise MXNetError(
                f"no model {name!r} registered "
                f"(have: {sorted(self._entries)})")
        return entry.engine

    def queue(self, name):
        return self._entries[name].queue

    def entry(self, name):
        """The (engine, queue, serial) record or None."""
        return self._entries.get(name)

    def entries(self):
        return list(self._entries.values())

    def sole_name(self):
        """The single registered model's name (the ``serve(model)``
        front end lets submit() omit it)."""
        names = list(self._entries)
        if len(names) != 1:
            raise MXNetError(
                "submit() needs an explicit model name with "
                f"{len(names)} models registered (have: {sorted(names)})")
        return names[0]

    # ---------------------------------------------------------- scheduling
    def _flush_at(self, entry):
        """The model's pad-vs-wait break-even instant (None if idle)."""
        q = entry.queue
        if not len(q):
            return None
        bucket = entry.engine.ladder.bucket_for(
            min(q.rows_pending, entry.engine.ladder.max))
        return q.flush_at(entry.engine.exec_estimate(bucket))

    def next_action(self, now):
        """('dispatch', name) | ('wait', seconds|None), mutating nothing.

        Ready = bucket full or past flush_at, AND the model's circuit
        breaker permits a dispatch at ``now``; ties break to the least
        recently dispatched model. A model whose breaker is open with
        queued work contributes its probe instant (cooldown expiry) to
        the wait bound instead. With work queued but nothing ready, the
        wait is until the earliest flush_at/probe; with no work at all
        the wait is unbounded (None — sleep until a submit signals).
        """
        ready, soonest = [], None
        for name, entry in self._entries.items():
            q = entry.queue
            if not len(q):
                continue
            if not entry.breaker.can_dispatch(now):
                probe_in = entry.breaker.retry_after(now)
                if probe_in > 0:
                    soonest = now + probe_in if soonest is None \
                        else min(soonest, now + probe_in)
                continue
            if q.rows_pending >= entry.engine.ladder.max:
                ready.append((entry.last_dispatch_seq, name))
                continue
            flush_at = self._flush_at(entry)
            if flush_at is not None and now >= flush_at:
                ready.append((entry.last_dispatch_seq, name))
            elif flush_at is not None:
                soonest = flush_at if soonest is None \
                    else min(soonest, flush_at)
        if ready:
            ready.sort()
            return "dispatch", ready[0][1]
        if soonest is not None:
            return "wait", max(0.0, soonest - now)
        return "wait", None

    def note_dispatch(self, name):
        """Bump the fairness serial for a dispatched model."""
        self._seq += 1
        self._entries[name].last_dispatch_seq = self._seq
