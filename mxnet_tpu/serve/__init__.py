"""Continuous-batching inference serving on the program cache.

Everything PRs 1–7 built for training amortization — the process-wide
program cache, persistent XLA cache, bucketed-shape modules, telemetry
and the flight recorder — is the hard half of a serving engine; this
package is the other half. In the style of Orca's iteration-level
scheduling and Clipper's deadline-aware adaptive batching:

* ``BucketEngine`` / ``PredictorEngine`` (engine.py) — pre-compiled
  forward programs over a configurable bucket ladder, warmed at startup
  through the program cache (and pinned there) so steady-state serving
  never compiles;
* ``AdmissionQueue`` + pad/slice helpers (batching.py) — coalesce
  requests into dynamic batches, pad to the nearest bucket, slice
  padded outputs back to per-request results;
* ``ModelRegistry`` (registry.py) — several models multi-tenant off one
  device pool, per-model ladders, deadline-aware fair scheduling;
* ``InferenceServer`` (server.py) — the in-process front end:
  ``serve(model).submit(inputs)`` returns a thread-safe sync+async
  ``ResponseHandle``; a dispatch thread (or an explicit deterministic
  ``pump()``) drives the scheduler;
* ``PoissonLoadGen`` (loadgen.py) — open-loop Poisson load generator
  for the req/s-at-p99-SLO benchmark axis (bench.py ``serve`` row).

Metrics (docs/serving.md has the catalog): ``serve.request.latency.
seconds`` histograms, ``serve.queue.depth`` / ``serve.batch.occupancy``
/ ``serve.padding.waste`` gauges, all exported by telemetry.prometheus,
plus a flight-ring record per dispatch.

Config: ``MXNET_SERVE_BUCKETS`` (default bucket ladder),
``MXNET_SERVE_MAX_QUEUE`` (admission bound), ``MXNET_SERVE_DEADLINE_MS``
(default request deadline) — docs/env_var.md.
"""
from __future__ import annotations

from ..faults import CircuitOpenError
from .clock import MonotonicClock, FakeClock
from .batching import (BucketLadder, QueueFullError, ResponseHandle,
                       ShedError, bucket_for, default_ladder, pad_rows,
                       slice_rows)
from .engine import BucketEngine, PredictorEngine
from .registry import ModelRegistry
from .server import InferenceServer, serve
from .warm import restore_server, save_server, server_payload
from .loadgen import PoissonLoadGen, run_scripted
from .decode import (DecodeEngine, DecodeHandle, DecodeScheduler,
                     default_prefill_chunk, default_slot_ladder,
                     default_spec_k, serve_decoder)
from .prefix import PrefixStore, default_prefix_budget_bytes
from .sampling import SamplingParams

__all__ = ["MonotonicClock", "FakeClock", "BucketLadder",
           "QueueFullError", "ShedError", "CircuitOpenError",
           "ResponseHandle", "bucket_for",
           "default_ladder", "pad_rows", "slice_rows", "BucketEngine",
           "PredictorEngine", "ModelRegistry", "InferenceServer",
           "serve", "restore_server", "save_server", "server_payload",
           "PoissonLoadGen", "run_scripted", "DecodeEngine",
           "DecodeScheduler", "DecodeHandle", "default_slot_ladder",
           "default_prefill_chunk", "default_spec_k", "PrefixStore",
           "default_prefix_budget_bytes", "SamplingParams",
           "serve_decoder"]
