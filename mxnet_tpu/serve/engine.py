"""Serving engines: pre-compiled bucket programs behind one forward().

Two backends, one contract — ``forward(bucket, values)`` runs the
pre-compiled program for one ladder rung over an assembled (already
padded) batch and returns the output NDArrays:

* ``BucketEngine`` — symbol + params. Internally a ``BucketingModule``
  whose bucket key IS the batch size: every rung is a Module bound
  ``for_training=False`` over a ``shared_module`` leader, so all rungs
  alias ONE set of parameter cells and each rung's forward program
  lands in the process-wide program cache under the normal executor
  keys. The inference forward path never donates buffers (the
  ``fwd_infer`` program is a plain jit with no ``donate_argnums``), so
  a batch assembled from caller arrays is never invalidated by
  dispatch — the donation-safe batched forward.
* ``PredictorEngine`` — an exported ``.mxp`` artifact served directly
  (predict.py): the ladder is the artifact's fixed exported batch size
  (re-export to change it) and the program is the deserialized
  StableHLO executable, no Symbol/Module stack in the process.

``warmup(clock)`` traces/compiles every rung (two forwards: the first
pays compile, the second measures steady-state execution on the given
clock — a FakeClock measures 0, which the deterministic scheduler tests
rely on), pins each rung's program in the program cache so a later
training rebind storm cannot evict a serving program, and records the
compile delta. After warmup, ``compiles_since_warmup()`` must stay 0 —
the acceptance contract bench.py's serve row and the e2e test assert.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import program_cache as _progcache
from ..base import MXNetError
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray
from .batching import BucketLadder

__all__ = ["BucketEngine", "PredictorEngine"]

log = logging.getLogger(__name__)


class _EngineBase:
    """Shared ladder/shape validation + warmup accounting."""

    def __init__(self, name, ladder):
        self.name = name
        self.ladder = ladder if isinstance(ladder, BucketLadder) \
            else BucketLadder(ladder)
        self.exec_est = {}            # bucket -> measured seconds (EMA'd
        self._warm_mark = None        # by the scheduler via note_exec)
        self.warmup_compiles = None

    # -- contract pieces subclasses fill in
    data_names = ()
    example_shapes = {}               # name -> per-row shape
    input_dtypes = {}                 # name -> numpy dtype

    def validate(self, inputs):
        """(rows, canonical dict) for one request's inputs; raises on a
        shape/name mismatch so bad requests fail at submit, not in the
        dispatch thread."""
        rows = None
        vals = {}
        for nm in self.data_names:
            if nm not in inputs:
                raise MXNetError(f"model {self.name!r}: missing input "
                                 f"{nm!r} (needs {list(self.data_names)})")
            arr = np.asarray(inputs[nm], dtype=self.input_dtypes[nm])
            want = self.example_shapes[nm]
            if arr.ndim != len(want) + 1 or tuple(arr.shape[1:]) != want:
                raise MXNetError(
                    f"model {self.name!r} input {nm!r}: shape "
                    f"{tuple(arr.shape)} != (rows,)+{want}")
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise MXNetError(
                    f"model {self.name!r}: inputs disagree on rows "
                    f"({rows} vs {arr.shape[0]} for {nm!r})")
            vals[nm] = arr
        if rows is None or rows < 1:
            raise MXNetError(f"model {self.name!r}: empty request")
        if rows > self.ladder.max:
            raise MXNetError(
                f"model {self.name!r}: {rows} rows exceed the largest "
                f"bucket {self.ladder.max} (extend the ladder or split "
                "the request)")
        return rows, vals

    def note_exec(self, bucket, seconds):
        """EMA the measured execution time into the flush estimate."""
        prev = self.exec_est.get(bucket)
        self.exec_est[bucket] = seconds if prev is None else \
            0.7 * prev + 0.3 * seconds

    def exec_estimate(self, bucket):
        """Execution-seconds estimate for a rung (0 until measured)."""
        if bucket in self.exec_est:
            return self.exec_est[bucket]
        known = [v for v in self.exec_est.values()]
        return max(known) if known else 0.0

    def warmup(self, clock):
        """Compile every rung, measure steady-state exec, pin programs."""
        mark = _progcache.compile_count()
        for bucket in self.ladder:
            zeros = {nm: np.zeros((bucket,) + self.example_shapes[nm],
                                  dtype=self.input_dtypes[nm])
                     for nm in self.data_names}
            self.forward(bucket, zeros)          # trace + compile
            t0 = clock.now()
            outs = self.forward(bucket, zeros)   # steady state
            for o in outs:
                np.asarray(o.asnumpy())          # force completion
            self.exec_est[bucket] = max(0.0, clock.now() - t0)
        self._pin_programs()
        self._warm_mark = _progcache.compile_count()
        self.warmup_compiles = self._warm_mark - mark
        return dict(self.exec_est)

    def compiles_since_warmup(self):
        """Fresh program-cache insertions since warmup finished (must be
        0 in steady state), or None before warmup."""
        if self._warm_mark is None:
            return None
        return _progcache.compile_count() - self._warm_mark

    def _pin_programs(self):
        pass

    def program_keys(self):
        """Process-cache keys of this engine's rung programs (may be
        empty for program stores outside the cache, e.g. Predictor)."""
        return []

    def programs_resident(self):
        """All rung programs still live in the process cache?"""
        keys = self.program_keys()
        return all(_progcache.contains(k) for k in keys) if keys else True


class BucketEngine(_EngineBase):
    """Symbol+params serving over a batch-size bucket ladder."""

    def __init__(self, name, symbol, arg_params, aux_params, data_shapes,
                 label_names=("softmax_label",), ladder=None, context=None,
                 compute_dtype=None, logger=None):
        """``data_shapes``: dict input name -> per-ROW shape (no batch
        dim) or list of ``(name, per_row_shape)``; the ladder supplies
        the batch dims. ``label_names`` are the loss-head inputs left
        unbound in inference mode (Module.predict semantics)."""
        super().__init__(name, ladder)
        from ..context import current_context
        from ..module import BucketingModule

        # compute_dtype="int8" / "fp8" selects a quantized inference
        # tier: the symbol is rewritten onto the Quantized* ops and
        # every dense/conv weight splits into a narrow storage cell
        # (int8 or float8_e4m3fn) + per-channel f32 scales
        # (ops/quant.py) BEFORE binding, so each ladder rung pins a
        # quantized program and the warm-restart payload (serve/
        # warm.py) persists the already-quantized symbol+params —
        # restores rebuild without re-quantizing. Activations stay
        # float; outputs sit within quant.INT8_TOL / quant.FP8_TOL of
        # the float ladder.
        self.quantized = None
        if compute_dtype is not None and str(compute_dtype) in (
                "int8", "fp8", "float8_e4m3fn"):
            from ..ops import quant as _quant
            symbol, arg_params = _quant.quantize_symbol(
                symbol, dict(arg_params or {}), dtype=str(compute_dtype))
            self.quantized = str(compute_dtype)
            compute_dtype = None

        if isinstance(data_shapes, dict):
            data_shapes = list(data_shapes.items())
        self.data_names = tuple(nm for nm, _ in data_shapes)
        self.example_shapes = {nm: tuple(s) for nm, s in data_shapes}
        self._symbol = symbol
        self._compute_dtype = compute_dtype     # for warm-restart payloads
        self._label_names = [nm for nm in (label_names or [])
                             if nm in symbol.list_arguments()]
        self._label_shape_cache = {}
        self._context = context if context is not None else current_context()

        # bucket key == batch size; every rung shares the leader's
        # parameter cells (shared_module bind) and its own cached
        # forward program
        self._bm = BucketingModule(
            sym_gen=lambda bucket: (symbol, list(self.data_names),
                                    list(self._label_names)),
            default_bucket_key=self.ladder.max,
            logger=logger or log, context=self._context)
        # BucketingModule's Module kwargs don't carry compute_dtype;
        # thread it through the per-bucket Module constructor args
        if compute_dtype is not None:
            self._bm._module_kwargs["compute_dtype"] = compute_dtype
        # loss-head labels are bound per bucket (zero-filled, ignored by
        # inference) — leaving label_shapes=None would classify the
        # label as a shared PARAM cell and alias the leader's
        # batch-sized label array into every rung
        self._bm.bind(self._provide_data(self.ladder.max),
                      label_shapes=self._provide_label(self.ladder.max),
                      for_training=False)
        self._bm.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params)
        self._bm.warm_buckets(
            [(b, self._provide_data(b), self._provide_label(b))
             for b in self.ladder])

        # recorded input dtypes come from the bound arrays (what the
        # compiled program actually takes — bf16 under compute_dtype)
        leader = self._bm._buckets[self.ladder.max]
        arg_dict = leader._exec_group.executor.arg_dict
        self.input_dtypes = {
            nm: np.dtype(str(arg_dict[nm].dtype)) if nm in arg_dict
            else np.float32
            for nm in self.data_names}

    def _provide_data(self, bucket):
        return [DataDesc(nm, (bucket,) + self.example_shapes[nm],
                         dtype=self.input_dtypes.get(nm, np.float32))
                for nm in self.data_names]

    def _provide_label(self, bucket):
        """Label shapes for one rung, inferred from the symbol against
        the rung's data shapes (None when the head has no label)."""
        if not self._label_names:
            return None
        if bucket not in self._label_shape_cache:
            known = {nm: (bucket,) + self.example_shapes[nm]
                     for nm in self.data_names}
            inferred, _, _ = self._symbol.infer_shape(**known)
            by_name = dict(zip(self._symbol.list_arguments(), inferred))
            self._label_shape_cache[bucket] = [
                DataDesc(nm, by_name[nm]) for nm in self._label_names
                if by_name.get(nm) is not None]
        return self._label_shape_cache[bucket] or None

    def forward(self, bucket, values):
        """Run the bucket program over one assembled batch (``values``:
        name -> array with exactly ``bucket`` rows)."""
        if bucket not in self.ladder.sizes:
            raise MXNetError(f"model {self.name!r}: {bucket} is not a "
                             f"ladder rung {self.ladder.sizes}")
        batch = DataBatch(
            data=[NDArray(np.ascontiguousarray(values[nm]),
                          ctx=self._context)
                  for nm in self.data_names],
            label=None, bucket_key=bucket,
            provide_data=self._provide_data(bucket),
            provide_label=self._provide_label(bucket))
        self._bm.forward(batch, is_train=False)
        return self._bm.get_outputs()

    @property
    def output_names(self):
        return self._bm._leader.output_names

    def program_keys(self):
        keys = []
        for bucket, mod in self._bm._buckets.items():
            key = mod._exec_group.executor.program_cache_key("fwd_infer")
            if key is not None:
                keys.append(key)
        return keys

    def _pin_programs(self):
        for key in self.program_keys():
            if not _progcache.pin(key):
                log.warning("serve %r: bucket program not resident at "
                            "pin time (cache capacity too small for the "
                            "ladder? MXNET_PROGRAM_CACHE_SIZE)", self.name)


class PredictorEngine(_EngineBase):
    """Serve an exported ``.mxp`` artifact directly (predict.py).

    The exported program's shapes are fixed at export time, so the
    ladder is the single exported batch size; requests pad into it.
    Re-export at other batch sizes (or use ``BucketEngine``) for a
    multi-rung ladder.
    """

    def __init__(self, name, predictor, ladder=None):
        from ..predict import Predictor
        # keep the artifact path (when there is one) so warm restarts
        # can re-register this engine from disk (serve/warm.py)
        self._path = predictor if isinstance(predictor, str) \
            else getattr(predictor, "_path", None)
        if isinstance(predictor, str):
            predictor = Predictor(predictor)
        self._pred = predictor
        shapes = predictor.input_shapes
        batches = {s[0] for s in shapes.values()}
        if len(batches) != 1:
            raise MXNetError(
                f"model {name!r}: exported inputs disagree on the batch "
                f"dim ({sorted(batches)}); cannot derive a bucket")
        exported = batches.pop()
        if ladder is not None and list(BucketLadder(ladder)) != [exported]:
            raise MXNetError(
                f"model {name!r}: a .mxp artifact serves only its "
                f"exported batch size {exported}; re-export to change "
                "the ladder")
        super().__init__(name, [exported])
        self.data_names = tuple(shapes)
        self.example_shapes = {nm: tuple(s[1:])
                               for nm, s in shapes.items()}
        self.input_dtypes = {nm: np.dtype(predictor.input_dtypes.get(
            nm, "float32")) for nm in shapes}

    def forward(self, bucket, values):
        if bucket != self.ladder.max:
            raise MXNetError(f"model {self.name!r}: exported batch is "
                             f"{self.ladder.max}, got bucket {bucket}")
        return self._pred.forward(**values)

    def warmup(self, clock):
        """Base warmup, then rewind a stateful artifact's carried state:
        the warmup forwards advance a KV-cache decoder's cache with
        zero-token garbage, and served decode steps must start from the
        exported snapshot. A stateful engine also opens its decode
        *session trace* here: every submit against it joins ONE trace
        (telemetry.trace), so an N-token decode reconstructs to a
        single span tree under the session root."""
        est = super().warmup(clock)
        if getattr(self._pred, "stateful", False):
            self._pred.reset_state()
            from ..telemetry import trace as _trace
            self.session_trace = _trace.new_trace(session=True)
        return est

    def reset_session(self):
        """Rewind the decoder state AND rotate the session trace — the
        next submit starts a fresh decode session/tree."""
        if getattr(self._pred, "stateful", False):
            self._pred.reset_state()
            from ..telemetry import trace as _trace
            self.session_trace = _trace.new_trace(session=True)

    @property
    def output_names(self):
        return self._pred.output_names
