"""Continuous decode batching: iteration-level scheduling over a
slot-pooled KV cache (the Orca-style serving path, ROADMAP 3b).

``InferenceServer`` batches one-shot requests; a KV-cache decoder is a
*sequence* — hundreds of single-token dispatches carrying device state
between them — and serving it one sequence at a time pins decode
throughput at batch 1. This module serves SLOTS sequences through ONE
pinned program per iteration:

* ``DecodeEngine`` — a slot-capacity rung ladder (``MXNET_SERVE_DECODE_
  SLOTS``, default ``1,4,8``) over ``get_decode_symbol(per_slot=True)``
  graphs: every rung is a Module bound at ``(slots, 1)`` sharing ONE
  set of parameter cells (``BucketingModule``/shared_module, exactly
  like the batch bucket ladder) with its own slot-pooled
  ``(slots, H, C, Dh)`` KV-cache aux; ``warmup`` compiles and PINS
  every rung, after which join/leave/rung-switches never mint a trace —
  ``compiles_since_warmup()`` stays 0. Rung switches migrate the live
  slots' cache rows + cursors between rung pools with eager per-row
  copies (no program-cache entries).
* ``DecodeScheduler`` — iteration-level continuous batching on the
  ``submit`` seam: prefill admission into free slots, per-iteration
  retirement (EOS / max-new-tokens / deadline / per-slot cache
  overflow — an overflowing slot fails ALONE, batchmates keep
  decoding), temperature/top-k/top-p sampling on a recorded
  per-request rng chain (``SamplingParams``; default greedy), and
  streaming token delivery through ``DecodeHandle`` callbacks. Two
  drive modes, same as the server: ``start()`` (dispatch thread, real
  clock) and ``pump()`` (explicit iterations, FakeClock-deterministic).

Three decode fast paths ride the same rungs (all preserve the
zero-steady-state-compile contract — every program they need is
compiled and pinned at warmup):

* **Chunked prefill** — each rung carries an S-token *window* program
  (``MXNET_SERVE_PREFILL_CHUNK``, default 64) next to its S=1 decode
  program, so a T-token prompt prefills in ⌈T/S⌉ dispatches instead of
  T and TTFT goes near-flat in prompt length. Slots mid-decode ride a
  chunk dispatch with one real token plus pads and REWIND their cursor
  afterwards (a join-style aux poke), so mixed prefill/decode
  iterations lose nothing.
* **Prefix-cache reuse** — ``submit(prefix_id=...)`` names a shared
  prompt prefix; the first completion snapshots its cache rows into a
  ``PrefixStore`` (LRU under ``MXNET_SERVE_PREFIX_CACHE_MB``, charged
  by the static memory planner) and later submits *join at cursor C*
  with the rows written back — bitwise what a cold prefill computes.
* **Speculative decoding** — a draft engine proposes
  ``MXNET_SERVE_SPEC_K`` tokens per iteration (K cheap S=1 dispatches)
  and the target verifies them in ONE S=K window dispatch over the
  per-slot cursor vector (slots verify at staggered positions); exact
  rejection sampling keeps the output distributionally identical to
  target-only decode and bit-identical under greedy, with rejected
  tails rolled back by cursor rewind on both engines.

Per-sequence traces survive being batched with strangers: every
sequence keeps its own session trace (root span
``serve.decode.sequence``), and each iteration records ONE shared
``serve.decode.step`` span id mirrored into every active sequence's
trace — the same shared-dispatch-span contract batched requests follow.

Telemetry (always on, docs/serving.md has the catalog):
``serve.decode.slots``/``active``/``occupancy``/``queue.depth`` gauges,
``serve.decode.iterations``/``tokens``/``joins``/``leaves``/
``migrations``/``requests``/``responses``/``errors`` counters,
``serve.decode.step.seconds`` + ``serve.decode.request.latency.seconds``
histograms, and one flight-ring record per iteration.
"""
from __future__ import annotations

import itertools
import logging
import os
import threading

import numpy as np

from .. import program_cache as _progcache
from .. import telemetry as _telemetry
from ..telemetry import trace as _trace
from ..base import MXNetError
from ..io import DataDesc
from .batching import BucketLadder, QueueFullError
from .clock import MonotonicClock
from .prefix import PrefixStore
from .sampling import SamplingParams, sample_token, token_probs, \
    speculative_verify

__all__ = ["DecodeEngine", "DecodeScheduler", "DecodeHandle",
           "default_slot_ladder", "default_prefill_chunk",
           "default_spec_k", "serve_decoder"]

log = logging.getLogger(__name__)

_seq_ids = itertools.count()

_GREEDY = SamplingParams()


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_prefill_chunk():
    """``MXNET_SERVE_PREFILL_CHUNK`` (docs/env_var.md), default 64:
    prompt tokens per prefill dispatch. 1 disables chunking (token-at-
    a-time prefill, the pre-window behavior)."""
    return max(1, _env_int("MXNET_SERVE_PREFILL_CHUNK", 64))


def default_spec_k():
    """``MXNET_SERVE_SPEC_K`` (docs/env_var.md), default 4: draft
    tokens proposed (and verified in one window dispatch) per
    speculative iteration."""
    return max(2, _env_int("MXNET_SERVE_SPEC_K", 4))


def default_slot_ladder():
    """The slot-capacity rung ladder from ``MXNET_SERVE_DECODE_SLOTS``
    (default ``1,4,8``): comma-separated concurrent-sequence capacities,
    sorted ascending, duplicates dropped — the decode-side analog of
    ``MXNET_SERVE_BUCKETS``."""
    raw = os.environ.get("MXNET_SERVE_DECODE_SLOTS", "1,4,8")
    try:
        sizes = sorted({int(tok) for tok in raw.split(",") if tok.strip()})
    except ValueError:
        raise MXNetError(f"MXNET_SERVE_DECODE_SLOTS={raw!r}: expected "
                         "comma-separated slot counts")
    if not sizes or sizes[0] < 1:
        raise MXNetError(f"MXNET_SERVE_DECODE_SLOTS={raw!r}: slot "
                         "counts must be >= 1")
    return sizes


class _Sequence:
    """One admitted decode request's scheduling state.

    The *stream* is ``prompt ++ generated``; ``fed`` counts stream
    tokens whose cache rows are written (= the slot's device cursor).
    An iteration feeds ``stream[fed : fed + n]`` in one dispatch and
    advances ``fed`` by the tokens it actually committed — in steady
    state ``fed == stream_len() - 1`` (the last sampled token is fed
    next), during prefill ``stream_len() - fed > 1``.
    """

    __slots__ = ("id", "prompt", "max_new", "eos_id", "arrival",
                 "deadline", "trace", "root_sid", "handle", "fed",
                 "generated", "slot", "finish_reason", "sampling",
                 "rng", "first_dispatch_at", "prefix_id", "prefix_cold")

    def __init__(self, prompt, max_new, eos_id, arrival, deadline,
                 trace=None, sampling=None, prefix_id=None):
        self.id = next(_seq_ids)
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.arrival = arrival
        self.deadline = deadline          # absolute clock s, or None
        self.trace = trace
        self.root_sid = None
        self.fed = 0                      # stream tokens fed = cursor
        self.generated = []
        self.slot = None
        self.finish_reason = None
        self.sampling = sampling if sampling is not None else _GREEDY
        self.rng = self.sampling.make_rng()
        self.first_dispatch_at = None     # first dispatch covering us
        self.prefix_id = prefix_id
        self.prefix_cold = False          # missed: capture after prefill
        self.handle = DecodeHandle(self)

    def stream_len(self):
        return len(self.prompt) + len(self.generated)

    def stream_token(self, i):
        if i < len(self.prompt):
            return int(self.prompt[i])
        return int(self.generated[i - len(self.prompt)])

    def remaining(self):
        """Stream tokens not yet fed (1 in steady state; > 1 while
        prefilling)."""
        return self.stream_len() - self.fed

    def window(self, n):
        """The next ``n`` stream tokens to feed."""
        return [self.stream_token(self.fed + j) for j in range(n)]


class DecodeHandle:
    """Streaming sync+async result surface for one decode request.

    Mirrors ``ResponseHandle`` (``done()``/``result()``/
    ``add_done_callback``/``latency``) and adds the streaming half:
    ``add_token_callback(fn)`` runs ``fn(handle, token, index)`` for
    every generated token — already-emitted tokens replay immediately
    on registration, so a late subscriber misses nothing. ``result()``
    returns the generated ids as an int32 numpy array (EOS excluded);
    ``finish_reason`` is ``"eos"``, ``"length"`` (max-new-tokens),
    ``"deadline"`` (partial result, deadline passed mid-decode), or
    None when the sequence errored (``exception()`` carries it).
    """

    def __init__(self, request):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._done_callbacks = []
        self._token_callbacks = []
        self._tokens = []
        self._error = None
        self.request = request
        self.completed_at = None        # scheduler-clock seconds
        self.first_token_at = None

    def done(self):
        return self._event.is_set()

    @property
    def trace_id(self):
        tr = self.request.trace
        return tr.trace_id if tr is not None else None

    @property
    def tokens(self):
        """Generated token ids so far (list copy — streaming-safe)."""
        with self._lock:
            return list(self._tokens)

    @property
    def finish_reason(self):
        return self.request.finish_reason

    @property
    def latency(self):
        """Admission-to-completion seconds (None until done)."""
        if self.completed_at is None:
            return None
        return self.completed_at - self.request.arrival

    @property
    def ttft(self):
        """Submit-to-first-token seconds, queue wait INCLUDED (None
        before the first token)."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.request.arrival

    @property
    def ttft_exec(self):
        """First-dispatch-to-first-token seconds: the prefill cost the
        engine actually paid, with queue wait excluded — the number the
        chunked-prefill win shows up in under load."""
        if self.first_token_at is None or \
                self.request.first_dispatch_at is None:
            return None
        return self.first_token_at - self.request.first_dispatch_at

    def missed_deadline(self):
        return (self.completed_at is not None
                and self.request.deadline is not None
                and self.completed_at > self.request.deadline)

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise MXNetError(
                f"decode request {self.request.id} not complete within "
                f"{timeout}s (scheduler stopped or stuck?)")
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int32)

    def exception(self):
        return self._error if self._event.is_set() else None

    def add_done_callback(self, fn):
        with self._lock:
            if not self._event.is_set():
                self._done_callbacks.append(fn)
                return
        fn(self)

    def add_token_callback(self, fn):
        """Stream generated tokens: ``fn(handle, token, index)`` per
        token, starting with an immediate replay of any already
        emitted."""
        with self._lock:
            replay = list(enumerate(self._tokens))
            self._token_callbacks.append(fn)
        for i, tok in replay:
            self._safe(fn, tok, i)

    def _safe(self, fn, *args):
        try:
            fn(self, *args)
        except Exception:       # a client callback must not kill the
            pass                # scheduler thread

    def _emit(self, token, now=None):
        with self._lock:
            index = len(self._tokens)
            self._tokens.append(int(token))
            cbs = list(self._token_callbacks)
        if index == 0:
            self.first_token_at = now
        for fn in cbs:
            self._safe(fn, int(token), index)

    def _complete(self, error=None, now=None):
        with self._lock:
            self._error = error
            self.completed_at = now
            callbacks, self._done_callbacks = self._done_callbacks, []
            self._event.set()
        for fn in callbacks:
            self._safe(fn)


class DecodeEngine:
    """Slot-capacity rung ladder over a slot-pooled decode graph.

    ``symbol`` must be a per-slot stateful decode graph (for the LM
    workload: ``models.transformer.get_decode_symbol(per_slot=True)``)
    whose batch dim is the slot count — the SAME symbol binds at every
    rung, so all rungs share one parameter-cell set through the bucket
    leader while each owns its rung-sized KV-cache pool. ``capacity``
    defaults to the bound cache's (inferred from the aux shapes);
    ``pos_embed`` is detected from the graph (a ``pos_ids`` argument =
    learned positions, fed per slot by the drivers).

    ``symbol_gen`` (``step_len -> symbol``, e.g.
    ``lambda s: get_decode_symbol(per_slot=True, step_len=s)``) arms
    the S>1 *window* programs: for every ``window_lens`` entry W > 1,
    each rung gets a Module over ``symbol_gen(W)`` bound with
    ``shared_module=`` that rung's S=1 module — parameter cells chain
    to the bucket leader's and the KV-cache/cursor aux CELLS are shared
    outright (their shapes are step-independent), so the window program
    and the decode program advance the same device state. Window
    lengths clamp to ``capacity``; all window programs warm and pin
    alongside the rungs' S=1 programs.
    """

    def __init__(self, name, symbol, arg_params, aux_params=None,
                 capacity=None, ladder=None, context=None,
                 compute_dtype=None, logger=None, symbol_gen=None,
                 window_lens=()):
        from ..context import current_context
        from ..module import BucketingModule

        self.name = name
        self.ladder = ladder if isinstance(ladder, BucketLadder) \
            else BucketLadder(ladder if ladder is not None
                              else default_slot_ladder())
        self.exec_est = {}              # rung -> EMA'd step seconds
        self._warm_mark = None
        self.warmup_compiles = None
        self._symbol = symbol
        self._context = context if context is not None \
            else current_context()
        self.pos_embed = "learned" \
            if "pos_ids" in symbol.list_arguments() else "rotary"
        self.data_names = ("data",) + (
            ("pos_ids",) if self.pos_embed == "learned" else ())
        if not any(getattr(n.opdef(), "stateful_infer", False)
                   for n in symbol._topo_nodes() if not n.is_variable):
            raise MXNetError(
                f"DecodeEngine({name!r}): the symbol has no stateful "
                "decode op (build it with get_decode_symbol("
                "per_slot=True))")

        self._bm = BucketingModule(
            sym_gen=lambda slots: (symbol, list(self.data_names), []),
            default_bucket_key=self.ladder.max,
            logger=logger or log, context=self._context)
        if compute_dtype is not None:
            self._bm._module_kwargs["compute_dtype"] = compute_dtype
        self._bm.bind(self._provide_data(self.ladder.max),
                      label_shapes=None, for_training=False)
        # straight to the leader with initializer=None: the decode
        # graph's aux states (KV cache + cursor) are absent from any
        # trained param set and must stay their bound zeros —
        # BucketingModule.init_params would fall back to Uniform and
        # trip over the cursor's name pattern
        self._bm._leader.init_params(initializer=None,
                                     arg_params=dict(arg_params or {}),
                                     aux_params=dict(aux_params or {}),
                                     allow_missing=True)
        self._bm.params_initialized = True
        self._bm._params_dirty = False
        self._bm.warm_buckets(
            [(s, self._provide_data(s), None) for s in self.ladder])

        if capacity is None:
            exe = self._bm._leader._exec_group.executor
            caches = [cell for nm, cell in exe.aux_dict.items()
                      if nm.endswith("k_cache")]
            if not caches:
                raise MXNetError(f"DecodeEngine({name!r}): no KV-cache "
                                 "aux state in the bound graph")
            capacity = caches[0].shape[2]
        self.capacity = int(capacity)

        from ..models.transformer import BatchedKVCacheDecoder
        self._drivers = {
            s: BatchedKVCacheDecoder(self._bm._buckets[s],
                                     self.capacity, slots=s,
                                     pos_embed=self.pos_embed)
            for s in self.ladder}

        self.window_lens = sorted(
            {min(int(w), self.capacity) for w in (window_lens or ())}
            - {0, 1})
        self._window_mods = {}               # (rung, S) -> Module
        if self.window_lens:
            if symbol_gen is None:
                raise MXNetError(
                    f"DecodeEngine({name!r}): window_lens="
                    f"{self.window_lens} needs symbol_gen= (a "
                    "step_len -> per-slot decode symbol factory)")
            self._build_windows(symbol_gen, compute_dtype,
                                logger or log)

    def _build_windows(self, symbol_gen, compute_dtype, logger):
        from ..module import Module
        for rung in self.ladder:
            base = self._bm._buckets[rung]
            b_exe = base._exec_group.executor
            for S in self.window_lens:
                mod = Module(symbol_gen(S),
                             data_names=list(self.data_names),
                             label_names=[], logger=logger,
                             context=self._context,
                             compute_dtype=compute_dtype)
                mod.bind(self._provide_data(rung, S),
                         label_shapes=None, for_training=False,
                         shared_module=base)
                w_exe = mod._exec_group.executor
                for nm, cell in w_exe.aux_dict.items():
                    if b_exe.aux_dict.get(nm) is not cell:
                        raise MXNetError(
                            f"DecodeEngine({self.name!r}): window "
                            f"step_len={S} did not share aux cell "
                            f"{nm!r} with the rung-{rung} decode "
                            "module — symbol_gen must rebuild the SAME "
                            "graph (names, capacity, slot count) at a "
                            "different step_len")
                self._drivers[rung].add_window(S, mod)
                self._window_mods[(rung, S)] = mod

    def _provide_data(self, slots, step=1):
        descs = [DataDesc("data", (slots, step), np.int32)]
        if self.pos_embed == "learned":
            descs.append(DataDesc("pos_ids", (slots, step), np.float32))
        return descs

    def driver(self, rung):
        """The rung's ``BatchedKVCacheDecoder``."""
        return self._drivers[rung]

    # ------------------------------------------------------------- warmup
    def warmup(self, clock):
        """Compile every slot rung's S=1 program AND every window
        program (two steps each: first pays the trace, second measures
        steady state on ``clock``), pin them all, record the compile
        delta. Warmup garbage stays harmless: afterwards every driver
        slot is free, every cursor is rewound to 0, and a join rewinds
        again."""
        mark = _progcache.compile_count()
        for rung in self.ladder:
            drv = self._drivers[rung]
            zeros = np.zeros((rung, 1), np.int32)
            drv.step(zeros).asnumpy()            # trace + compile
            t0 = clock.now()
            drv.step(zeros).asnumpy()            # steady state
            self.exec_est[rung] = max(0.0, clock.now() - t0)
            for S in drv.window_lens:
                wz = np.zeros((rung, S), np.int32)
                # rewind first so even tiny caches never see the
                # clamped dynamic_update_slice path during warmup
                drv.rewind_many(list(range(rung)), [0] * rung)
                drv.step(wz).asnumpy()           # trace + compile
                drv.rewind_many(list(range(rung)), [0] * rung)
                t0 = clock.now()
                drv.step(wz).asnumpy()           # steady state
                self.exec_est[(rung, S)] = max(0.0, clock.now() - t0)
            drv.active[:] = False
            drv.rewind_many(list(range(rung)), [0] * rung)
        self._pin_programs()
        self._warm_mark = _progcache.compile_count()
        self.warmup_compiles = self._warm_mark - mark
        return dict(self.exec_est)

    def note_exec(self, rung, seconds):
        prev = self.exec_est.get(rung)
        self.exec_est[rung] = seconds if prev is None else \
            0.7 * prev + 0.3 * seconds

    def exec_estimate(self, rung):
        if rung in self.exec_est:
            return self.exec_est[rung]
        known = list(self.exec_est.values())
        return max(known) if known else 0.0

    def compiles_since_warmup(self):
        if self._warm_mark is None:
            return None
        return _progcache.compile_count() - self._warm_mark

    def program_keys(self):
        keys = []
        for rung, mod in self._bm._buckets.items():
            key = mod._exec_group.executor.program_cache_key("fwd_infer")
            if key is not None:
                keys.append(key)
        for (_rung, _S), mod in self._window_mods.items():
            key = mod._exec_group.executor.program_cache_key("fwd_infer")
            if key is not None:
                keys.append(key)
        return keys

    def _pin_programs(self):
        for key in self.program_keys():
            if not _progcache.pin(key):
                log.warning(
                    "decode %r: rung program not resident at pin time "
                    "(cache capacity too small for the slot ladder? "
                    "MXNET_PROGRAM_CACHE_SIZE)", self.name)

    def programs_resident(self):
        keys = self.program_keys()
        return all(_progcache.contains(k) for k in keys) if keys else True

    # ---------------------------------------------------------- migration
    def migrate(self, src_rung, dst_rung, pairs):
        """Carry live slots between rung pools: for every (src_row,
        dst_row) pair, the slot's cache rows and cursor copy from the
        ``src_rung`` aux arrays into ``dst_rung``'s, and the host
        mirrors follow. Eager per-row gathers/scatters — nothing lands
        in the program cache, so rung switches keep the zero-compile
        contract."""
        if src_rung == dst_rung:
            return
        sdrv, ddrv = self._drivers[src_rung], self._drivers[dst_rung]
        s_exe = self._bm._buckets[src_rung]._exec_group.executor
        d_exe = self._bm._buckets[dst_rung]._exec_group.executor
        ddrv.active[:] = False
        if pairs:
            si = np.asarray([p[0] for p in pairs])
            di = np.asarray([p[1] for p in pairs])
            for nm, cell in s_exe.aux_dict.items():
                dcell = d_exe.aux_dict[nm]
                dcell._set(dcell.asjax().at[di].set(cell.asjax()[si]))
            for s_row, d_row in pairs:
                ddrv.pos[d_row] = sdrv.pos[s_row]
                ddrv.active[d_row] = True
        sdrv.active[:] = False


class DecodeScheduler:
    """Iteration-level continuous batching over one ``DecodeEngine``.

    ``submit(prompt)`` admits a sequence (``QueueFullError`` past
    ``MXNET_SERVE_DECODE_MAX_QUEUE``) and returns a streaming
    ``DecodeHandle``. Each scheduler iteration retires finished
    sequences (EOS / max-new / deadline / per-slot overflow), admits
    queued ones into free slots (growing the rung when the ladder
    allows), migrates live slots on rung switches, then advances every
    slot through the rung's pinned programs and streams the sampled
    tokens. Sampling is per request (``SamplingParams``; default
    greedy-argmax).

    Fast paths (each armed only when its programs were built at engine
    construction, so steady state never compiles): ``prefill_chunk``
    S>1 window dispatches while any slot is prefilling (decoding slots
    ride along with one real token + pads and rewind after);
    ``draft_engine`` + ``spec_k`` speculative iterations when every
    active slot is in steady state (K draft proposals, one S=K target
    verify, exact rejection, cursor rollback on both engines);
    ``prefix_store`` joins at cursor C on ``submit(prefix_id=...)``
    hits and snapshots cold prefixes when their prefill completes.
    """

    def __init__(self, engine, clock=None, max_queue=None,
                 default_max_new=None, logger=None, draft_engine=None,
                 prefill_chunk=None, spec_k=None, prefix_store=None):
        self.engine = engine
        self.draft = draft_engine
        self._clock = clock if clock is not None else MonotonicClock()
        self._max_queue = max_queue if max_queue is not None else \
            _env_int("MXNET_SERVE_DECODE_MAX_QUEUE", 256)
        self._default_max_new = default_max_new if default_max_new \
            is not None else _env_int("MXNET_SERVE_DECODE_MAX_NEW", 64)
        self.logger = logger or log

        if self.draft is not None:
            if list(self.draft.ladder.sizes) != list(engine.ladder.sizes):
                raise MXNetError(
                    f"draft engine ladder {self.draft.ladder.sizes} "
                    f"must match the target's {engine.ladder.sizes} "
                    "(slots mirror 1:1)")
            if self.draft.capacity < engine.capacity:
                raise MXNetError(
                    f"draft cache capacity {self.draft.capacity} < "
                    f"target capacity {engine.capacity}: the draft "
                    "tracks the same stream")
        chunk = int(prefill_chunk if prefill_chunk is not None
                    else default_prefill_chunk())
        chunk = min(chunk, engine.capacity)
        usable = set(engine.window_lens)
        if self.draft is not None:
            usable &= set(self.draft.window_lens)
        self.prefill_chunk = chunk if chunk > 1 and chunk in usable \
            else 1
        k = int(spec_k if spec_k is not None else default_spec_k())
        self.spec_k = 0
        if self.draft is not None:
            if k < 2 or k not in set(engine.window_lens):
                raise MXNetError(
                    f"speculative decoding armed (draft engine given) "
                    f"but the target has no step_len={k} verify window "
                    f"(windows: {engine.window_lens}); build the "
                    "engine with spec_k in window_lens")
            self.spec_k = k
        self.prefix_store = prefix_store
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rollbacks = 0

        # reentrant: completion/token callbacks run with the scheduler
        # lock held and may legitimately submit a follow-up sequence
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue = []
        self._rung = self.engine.ladder.sizes[0]
        self._slots = [None] * self._rung
        self._thread = None
        self._running = False
        self.iterations = 0
        self.migrations = 0
        # draft first: the target's post-warmup compile mark is the
        # zero-compile gate stats() reports, so it must be taken LAST
        if self.draft is not None:
            with _telemetry.span("serve.decode.warmup",
                                 model=self.draft.name):
                self.draft.warmup(self._clock)
        with _telemetry.span("serve.decode.warmup",
                             model=self.engine.name):
            est = self.engine.warmup(self._clock)
        if self.draft is not None:
            # the target's warmup compiles landed after the draft's
            # mark; refresh it so BOTH gates read 0 in steady state
            self.draft._warm_mark = _progcache.compile_count()
        self.logger.info(
            "decode %r warmed — slot ladder %s, windows %s, "
            "%d compiles, step est %s",
            self.engine.name, self.engine.ladder.sizes,
            self.engine.window_lens, self.engine.warmup_compiles,
            {r: f"{s * 1e3:.2f}ms" for r, s in est.items()})
        self._gauge("slots").set(self._rung)
        self._gauge("active").set(0)
        self._gauge("occupancy").set(0.0)
        self._gauge("queue.depth").set(0)

    def _gauge(self, key):
        return _telemetry.gauge(f"serve.decode.{key}",
                                model=self.engine.name)

    def _counter(self, key):
        return _telemetry.counter(f"serve.decode.{key}",
                                  model=self.engine.name)

    # ------------------------------------------------------------ admission
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, trace=None, sampling=None,
               prefix_id=None):
        """Admit one sequence: ``prompt`` is a 1-D int id sequence
        (1 <= len <= cache capacity). ``max_new_tokens`` caps
        generation (``MXNET_SERVE_DECODE_MAX_NEW`` default); ``eos_id``
        retires the sequence when sampled (not emitted);
        ``deadline_ms`` (relative to now) retires it mid-decode with a
        partial result and ``finish_reason="deadline"``. ``sampling``
        is a ``SamplingParams`` (default greedy-argmax; replaying the
        same params + prompt reproduces the token stream byte for
        byte). ``prefix_id`` names a shared prompt prefix for the
        prefix store: a hit joins at cursor C with donated cache rows,
        a miss prefills cold and snapshots the prompt's rows for the
        next submit. Returns the streaming ``DecodeHandle``."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise MXNetError("empty prompt")
        if prompt.size > self.engine.capacity:
            raise MXNetError(
                f"prompt of {prompt.size} tokens exceeds the decode "
                f"cache capacity {self.engine.capacity}")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self._default_max_new)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        now = self._clock.now()
        deadline = None if deadline_ms is None \
            else now + deadline_ms / 1000.0
        tr = trace
        if tr is None and _trace.sample():
            tr = _trace.new_trace(session=True)
        seq = _Sequence(prompt, max_new, eos_id, now, deadline, trace=tr,
                        sampling=sampling, prefix_id=prefix_id)
        if tr is not None:
            seq.root_sid = _trace.next_span_id()
            if tr.root is None:
                tr.root = seq.root_sid
            if tr.start_s is None:
                tr.start_s = now
        with self._cond:
            if len(self._queue) >= self._max_queue:
                exc = QueueFullError(
                    f"decode {self.engine.name!r}: queue depth "
                    f"{len(self._queue)} at MXNET_SERVE_DECODE_"
                    f"MAX_QUEUE={self._max_queue}")
                if tr is not None:
                    exc.trace_id = tr.trace_id
                _telemetry.counter("serve.rejected",
                                   model=self.engine.name).inc()
                raise exc
            self._queue.append(seq)
            depth = len(self._queue)
            self._cond.notify_all()
        self._counter("requests").inc()
        self._gauge("queue.depth").set(depth)
        return seq.handle

    # ----------------------------------------------------------- scheduling
    def _active(self):
        return [s for s in self._slots if s is not None]

    def _finish(self, seq, reason=None, error=None, now=None):
        """Complete a sequence's handle and free its slot (caller holds
        the lock)."""
        seq.finish_reason = reason
        if seq.slot is not None:
            self.engine.driver(self._rung).leave(seq.slot)
            if self.draft is not None:
                self.draft.driver(self._rung).leave(seq.slot)
            self._slots[seq.slot] = None
            seq.slot = None
            self._counter("leaves").inc()
        if seq.trace is not None:
            _trace.record(
                seq.trace, "serve.decode.sequence", seq.arrival,
                now if now is not None else self._clock.now(),
                span_id=seq.root_sid, model=self.engine.name,
                prompt=len(seq.prompt), generated=len(seq.generated),
                finish=reason if error is None else
                type(error).__name__)
            if error is not None:
                error.trace_id = seq.trace.trace_id
        self._counter("errors" if error is not None
                      else "responses").inc()
        if error is None:
            _telemetry.histogram(
                "serve.decode.request.latency.seconds",
                model=self.engine.name).observe(
                max(0.0, (now if now is not None else
                          self._clock.now()) - seq.arrival),
                exemplar=seq.trace.trace_id
                if seq.trace is not None else None)
        seq.handle._complete(error=error, now=now)

    def _switch_rung(self, target):
        """Migrate live slots into the ``target`` rung pool, compacting
        them into the lowest rows (caller holds the lock)."""
        pairs = []
        new_slots = [None] * target
        dst = 0
        for row, seq in enumerate(self._slots):
            if seq is None:
                continue
            pairs.append((row, dst))
            seq.slot = dst
            new_slots[dst] = seq
            dst += 1
        self.engine.migrate(self._rung, target, pairs)
        if self.draft is not None:
            self.draft.migrate(self._rung, target, pairs)
        self._rung = target
        self._slots = new_slots
        self.migrations += 1
        self._counter("migrations").inc()
        self._gauge("slots").set(target)

    def _admit_locked(self, now):
        """Retire expired queued requests, grow the rung if the backlog
        wants it, and fill free slots FIFO."""
        for seq in [s for s in self._queue
                    if s.deadline is not None and now > s.deadline]:
            self._queue.remove(seq)
            self._finish(seq, reason="deadline", now=now)
        if not self._queue:
            return
        want = min(len(self._active()) + len(self._queue),
                   self.engine.ladder.max)
        target = self.engine.ladder.bucket_for(max(want, 1))
        if target is not None and target > self._rung:
            self._switch_rung(target)
        drv = self.engine.driver(self._rung)
        for row in range(self._rung):
            if self._slots[row] is not None or not self._queue:
                continue
            seq = self._queue.pop(0)
            drv.join(row)
            if self.draft is not None:
                self.draft.driver(self._rung).join(row)
            seq.slot = row
            self._slots[row] = seq
            self._counter("joins").inc()
            if seq.prefix_id is not None and \
                    self.prefix_store is not None:
                self._prefix_admit(row, seq, now)
            if seq.trace is not None:
                _trace.record(seq.trace, "serve.decode.queue.wait",
                              seq.arrival, now, parent=seq.root_sid,
                              slot=row)

    def _prefix_admit(self, row, seq, now):
        """Prefix-store hit test for one freshly joined sequence: on a
        hit the slot *joins at cursor C* — the stored rows write back
        into its cache slice (bitwise what a cold prefill of those
        positions computes) and the cursor rewinds forward to C, so
        prefill starts at the first unshared token. A miss marks the
        sequence cold: its prompt rows snapshot into the store the
        iteration its prefill completes."""
        tags = ("target", "draft") if self.draft is not None \
            else ("target",)
        c, entry = self.prefix_store.lookup(seq.prefix_id, seq.prompt,
                                            tags=tags)
        if entry is None:
            seq.prefix_cold = True
            self._counter("prefix.misses").inc()
            return
        drv = self.engine.driver(self._rung)
        drv.restore_rows(row, {nm: r[:, :c]
                               for nm, r in entry.payloads["target"]
                               .items()})
        drv.rewind(row, c)
        if self.draft is not None:
            ddrv = self.draft.driver(self._rung)
            ddrv.restore_rows(row, {nm: r[:, :c]
                                    for nm, r in entry.payloads["draft"]
                                    .items()})
            ddrv.rewind(row, c)
        seq.fed = c
        self._counter("prefix.hits").inc()
        if seq.trace is not None:
            _trace.record(seq.trace, "serve.decode.prefix.join",
                          now, now, parent=seq.root_sid, slot=row,
                          cursor=c)

    def _plan_dispatch(self):
        """Pick this iteration's dispatch shape (caller holds the
        lock): ``("window", S)`` — every active slot feeds up to S
        stream tokens (S = prefill chunk while anyone prefills and
        every live cursor has room, else 1) — or ``("spec", K)`` when
        speculation is armed and every active slot is in steady state
        with K positions of cache headroom on both engines."""
        drv = self.engine.driver(self._rung)
        ddrv = self.draft.driver(self._rung) if self.draft else None
        prefilling = any(s.remaining() > 1 for s in self._active())
        if prefilling:
            S = self.prefill_chunk
            if S > 1 and not drv.overflowing(S) and \
                    (ddrv is None or not ddrv.overflowing(S)):
                return "window", S
            return "window", 1
        if self.spec_k and ddrv is not None and \
                not drv.overflowing(self.spec_k) and \
                not ddrv.overflowing(self.spec_k):
            return "spec", self.spec_k
        return "window", 1

    def _dispatch_spec(self, drv, ddrv, base_tokens, meta, K):
        """One speculative iteration's device work (runs OUTSIDE the
        scheduler lock, like every dispatch): K draft S=1 dispatches
        propose ``d_1..d_K`` per slot, then ONE target S=K window
        dispatch — window ``[t, d_1..d_{K-1}]`` at the slot's own
        cursor — yields the target distribution for every proposed
        position (row j verifies ``d_{j+1}``). Returns
        ``{row: (accepted, tokens)}`` from exact rejection sampling."""
        rung = base_tokens.shape[0]
        proposals = np.zeros((rung, K), np.int64)
        draft_rows = {row: [] for row, _seq in meta}
        feed = base_tokens.copy()
        for j in range(K):
            dlog = ddrv.step(feed).asnumpy()       # (rung, 1, V)
            feed = np.zeros((rung, 1), np.int32)
            for row, seq in meta:
                d = sample_token(dlog[row, 0], seq.sampling, seq.rng)
                proposals[row, j] = d
                draft_rows[row].append(dlog[row, 0])
                feed[row, 0] = d
        window = np.zeros((rung, K), np.int32)
        window[:, 0] = base_tokens[:, 0]
        if K > 1:
            window[:, 1:] = proposals[:, :K - 1]
        vlog = drv.step(window).asnumpy()          # (rung, K, V)
        out = {}
        for row, seq in meta:
            out[row] = speculative_verify(
                vlog[row], np.asarray(draft_rows[row]),
                proposals[row], seq.sampling, seq.rng)
        return out

    def _iterate(self):
        """One scheduling iteration; returns tokens emitted (0 = no
        work was ready)."""
        with self._lock:
            now = self._clock.now()
            # retirement BEFORE dispatch: deadline-expired sequences
            # complete with their partial output; a slot whose next
            # token would overflow its cache slice fails ALONE — the
            # program was never dispatched for it, batchmates continue
            for seq in list(self._active()):
                if seq.deadline is not None and now > seq.deadline:
                    self._finish(seq, reason="deadline", now=now)
            for row in self.engine.driver(self._rung).overflowing():
                seq = self._slots[row]
                if seq is None:          # retired row still advancing
                    continue
                self._finish(seq, error=MXNetError(
                    f"decode {self.engine.name!r}: sequence {seq.id} "
                    f"overflowed its KV-cache slice (slot {row}, "
                    f"capacity {self.engine.capacity}); shorten the "
                    "prompt/max_new_tokens or re-bind with a larger "
                    "capacity"), now=now)
            self._admit_locked(now)
            active = self._active()
            if not active:
                self._gauge("active").set(0)
                self._gauge("occupancy").set(0.0)
                return 0
            # shrink to the smallest rung covering the live set (frees
            # the larger pool's compute for the next iterations)
            target = self.engine.ladder.bucket_for(len(active))
            if target is not None and target < self._rung:
                self._switch_rung(target)
            drv = self.engine.driver(self._rung)
            ddrv = self.draft.driver(self._rung) if self.draft else None
            mode, S = self._plan_dispatch()
            meta = []                    # (row, seq[, n_fed]) rows
            if mode == "spec":
                tokens = np.zeros((self._rung, 1), np.int32)
                for row, seq in enumerate(self._slots):
                    if seq is None:
                        continue
                    tokens[row, 0] = seq.stream_token(seq.fed)
                    meta.append((row, seq))
            else:
                tokens = np.zeros((self._rung, S), np.int32)
                for row, seq in enumerate(self._slots):
                    if seq is None:
                        continue
                    n = min(S, seq.remaining())
                    tokens[row, :n] = seq.window(n)
                    meta.append((row, seq, n))
            for entry in meta:
                seq = entry[1]
                if seq.first_dispatch_at is None:
                    seq.first_dispatch_at = now
            active = list(self._active())
            shared_sid = _trace.next_span_id() \
                if any(s.trace is not None for s in active) else None
            t0 = now

        # dispatch outside the lock: submits stay non-blocking while
        # the program runs (only pump()/the dispatch thread iterates,
        # so the engine itself needs no second guard)
        if mode == "spec":
            verdicts = self._dispatch_spec(
                drv, ddrv, tokens, [(r, s) for r, s in meta], S)
        else:
            logits = drv.step(tokens).asnumpy()    # (rung, S, V)
            if ddrv is not None:
                # the draft shadows every non-speculative dispatch so
                # its cache tracks the same stream positions
                ddrv.step(tokens).asnumpy()

        with self._lock:
            end = self._clock.now()
            step_s = max(0.0, end - t0)
            self.engine.note_exec(self._rung if S == 1
                                  else (self._rung, S), step_s)
            emitted = 0
            chunks = 0
            rew_rows, rew_pos = [], []
            if mode == "spec":
                emitted = self._commit_spec(
                    meta, verdicts, S, t0, end, shared_sid,
                    rew_rows, rew_pos)
            else:
                for row, seq, n in meta:
                    if seq.slot is None:
                        continue
                    was_prefilling = seq.remaining() > 1
                    samples = seq.fed + n == seq.stream_len()
                    if seq.trace is not None:
                        _trace.record(
                            seq.trace, "serve.decode.step", t0, end,
                            span_id=shared_sid, parent=seq.root_sid,
                            rung=self._rung, n_active=len(active),
                            shared=True, pos=seq.fed, window=n)
                        if was_prefilling:
                            _trace.record(
                                seq.trace, "serve.decode.prefill",
                                t0, end, parent=seq.root_sid,
                                pos=seq.fed, tokens=n, chunk=S)
                    if was_prefilling:
                        chunks += 1
                    tok = sample_token(logits[row, n - 1],
                                       seq.sampling, seq.rng) \
                        if samples else None
                    seq.fed += n
                    if n < S:
                        # the dispatch advanced the cursor by S; pull
                        # it back to the stream position actually fed
                        rew_rows.append(row)
                        rew_pos.append(seq.fed)
                    self._capture_prefix(seq, end)
                    if not samples:
                        continue              # still prefilling
                    if seq.eos_id is not None and tok == seq.eos_id:
                        self._finish(seq, reason="eos", now=end)
                        continue            # EOS retires, not emitted
                    seq.generated.append(tok)
                    seq.handle._emit(tok, now=end)
                    emitted += 1
                    if len(seq.generated) >= seq.max_new:
                        self._finish(seq, reason="length", now=end)
            # retired rows keep advancing one window per dispatch; pull
            # any nearing capacity back to 0 so no dispatch ever sees a
            # clamped window write for a row nobody owns
            maxw = max([1] + list(drv.window_lens))
            seen = set(rew_rows)
            for row in range(self._rung):
                if self._slots[row] is None and row not in seen and \
                        drv.pos[row] + maxw > self.engine.capacity:
                    rew_rows.append(row)
                    rew_pos.append(0)
            if rew_rows:
                drv.rewind_many(rew_rows, rew_pos)
                if ddrv is not None:
                    ddrv.rewind_many(rew_rows, rew_pos)
            self.iterations += 1
            n_active = len(self._active())
            self._counter("iterations").inc()
            if emitted:
                self._counter("tokens").inc(emitted)
            if chunks:
                self._counter("prefill.chunks").inc(chunks)
            _telemetry.histogram("serve.decode.step.seconds",
                                 model=self.engine.name).observe(step_s)
            self._gauge("active").set(n_active)
            self._gauge("occupancy").set(n_active / self._rung)
            self._gauge("queue.depth").set(len(self._queue))
            compiles = self.engine.compiles_since_warmup()
            _telemetry.gauge(
                "serve.program_cache.compiles_since_warmup").set(
                compiles or 0)
            _telemetry.flightrec.note(
                "serve.decode.step", model=self.engine.name,
                rung=self._rung, active=n_active, emitted=emitted,
                step_us=int(step_s * 1e6), mode=mode, window=S,
                compiles_since_warmup=compiles)
        return max(1, emitted)

    def _commit_spec(self, meta, verdicts, K, t0, end, shared_sid,
                     rew_rows, rew_pos):
        """Apply one speculative iteration's verdicts (caller holds the
        lock): commit each slot's accepted prefix + rejection sample,
        stream the tokens, roll the cursor back over the rejected tail
        (both engines, via the caller's rewind batch), retire on EOS /
        max-new mid-window (tokens past the stop are discarded — the
        target never sampled them)."""
        emitted = 0
        for row, seq in meta:
            if seq.slot is None:
                continue
            accepted, toks = verdicts[row]
            self.spec_proposed += K
            self.spec_accepted += accepted
            if accepted < K:
                self.spec_rollbacks += 1
            committed = 0
            finish = None
            for tok in toks:
                if seq.eos_id is not None and tok == seq.eos_id:
                    finish = "eos"
                    break
                seq.generated.append(int(tok))
                seq.handle._emit(int(tok), now=end)
                emitted += 1
                committed += 1
                if len(seq.generated) >= seq.max_new:
                    finish = "length"
                    break
            seq.fed += committed
            if committed < K:
                rew_rows.append(row)
                rew_pos.append(seq.fed)
            if seq.trace is not None:
                _trace.record(
                    seq.trace, "serve.decode.step", t0, end,
                    span_id=shared_sid, parent=seq.root_sid,
                    rung=self._rung, shared=True, pos=seq.fed,
                    spec_k=K, accepted=accepted, committed=committed)
            if finish is not None:
                self._finish(seq, reason=finish, now=end)
        self._counter("spec.proposed").inc(K * len(meta))
        accepted_now = sum(verdicts[r][0] for r, _ in meta)
        if accepted_now:
            self._counter("spec.accepted").inc(accepted_now)
        return emitted

    def _capture_prefix(self, seq, now):
        """Snapshot a cold prefix the moment its prefill completes
        (caller holds the lock): the slot's first ``len(prompt)`` cache
        positions on the target (and draft, when armed) plus the token
        ids they encode."""
        if not seq.prefix_cold or self.prefix_store is None or \
                seq.slot is None or seq.fed < len(seq.prompt):
            return
        payloads = {"target": self.engine.driver(self._rung)
                    .capture_rows(seq.slot, len(seq.prompt))}
        if self.draft is not None:
            payloads["draft"] = self.draft.driver(self._rung) \
                .capture_rows(seq.slot, len(seq.prompt))
        stored = self.prefix_store.put(
            seq.prefix_id, np.asarray(seq.prompt, np.int64), payloads)
        seq.prefix_cold = False
        if stored:
            self._counter("prefix.captures").inc()

    # ----------------------------------------------------------- drive modes
    def _has_work(self):
        return bool(self._queue) or any(
            s is not None for s in self._slots)

    def pump(self, max_iterations=None):
        """Deterministic drive: run scheduler iterations until nothing
        is active or queued (or ``max_iterations``). The FakeClock
        path — no thread, no sleeps. Returns iterations run."""
        done = 0
        while max_iterations is None or done < max_iterations:
            with self._lock:
                if not self._has_work():
                    break
            emitted = self._iterate()
            with self._lock:
                if emitted == 0 and not self._queue:
                    break
            done += 1
        return done

    def _loop(self):
        while True:
            with self._cond:
                if not self._running:
                    return
                if not self._has_work():
                    # bounded wait so queued-request deadlines are
                    # noticed; a submit notifies sooner
                    self._cond.wait(timeout=0.05)
                    continue
            self._iterate()

    def start(self):
        """Spawn the decode dispatch thread (idempotent)."""
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="mxnet-serve-decode",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True):
        """Stop the thread; ``drain`` finishes in-flight and queued
        sequences first, else they fail with MXNetError."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        if drain:
            self.pump()
        else:
            with self._lock:
                now = self._clock.now()
                for seq in list(self._active()) + self._queue:
                    self._finish(seq, error=MXNetError(
                        "decode scheduler stopped"), now=now)
                self._queue = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Snapshot for dashboards/bench: slot occupancy, queue depth,
        token/iteration counters, per-rung step estimates, and the
        zero-compile gate reading."""

        def c(key):
            m = _telemetry.get_metric(f"serve.decode.{key}",
                                      model=self.engine.name)
            return m.value if m is not None else 0

        with self._lock:
            n_active = len(self._active())
            depth = len(self._queue)
            rung = self._rung
            spec_proposed = self.spec_proposed
            spec_accepted = self.spec_accepted
            spec_rollbacks = self.spec_rollbacks
        h = _telemetry.get_metric("serve.decode.request.latency.seconds",
                                  model=self.engine.name)
        its = c("iterations")
        # exec_est keys mix rungs (int) and (rung, window) tuples —
        # render both as strings ("8", "8xS64") for a stable sort
        exec_est = {
            (f"{k[0]}xS{k[1]}" if isinstance(k, tuple) else str(k)):
            round(s * 1e3, 3) for k, s in self.engine.exec_est.items()}
        out = {
            "model": self.engine.name,
            "ladder": self.engine.ladder.sizes,
            "rung": rung,
            "active": n_active,
            "occupancy": round(n_active / rung, 4) if rung else None,
            "queue_depth": depth,
            "requests": c("requests"),
            "responses": c("responses"),
            "errors": c("errors"),
            "iterations": its,
            "tokens": c("tokens"),
            "tokens_per_iteration": round(c("tokens") / its, 3)
            if its else None,
            "joins": c("joins"),
            "leaves": c("leaves"),
            "migrations": c("migrations"),
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks": c("prefill.chunks"),
            "latency_ms": None if h is None or not h.count else {
                "p50": round((h.quantile(0.50) or 0) * 1e3, 3),
                "p99": round((h.quantile(0.99) or 0) * 1e3, 3),
                "mean": round(h.mean * 1e3, 3)},
            "exec_est_ms": dict(sorted(exec_est.items())),
            "capacity": self.engine.capacity,
            "compiles_since_warmup": self.engine.compiles_since_warmup(),
            "programs_resident": self.engine.programs_resident(),
        }
        if self.spec_k:
            out["spec"] = {
                "k": self.spec_k,
                "proposed": spec_proposed,
                "accepted": spec_accepted,
                "rollbacks": spec_rollbacks,
                "acceptance": round(spec_accepted / spec_proposed, 4)
                if spec_proposed else None,
            }
        if self.prefix_store is not None:
            out["prefix"] = self.prefix_store.stats()
        return out


def serve_decoder(symbol, arg_params, name="decoder", capacity=None,
                  ladder=None, clock=None, start=True, max_queue=None,
                  default_max_new=None, context=None, compute_dtype=None,
                  logger=None, symbol_gen=None, prefill_chunk=None,
                  draft_symbol_gen=None, draft_params=None, spec_k=None,
                  prefix_cache_mb=None):
    """One-call front end for continuous decode batching:
    ``serve_decoder(decode_symbol, params).submit([ids...])``.

    ``symbol`` is a per-slot decode graph
    (``get_decode_symbol(per_slot=True)``); builds the slot-rung
    ``DecodeEngine``, warms+pins every rung, and (by default) starts
    the dispatch thread — ``start=False`` + ``pump()`` with a FakeClock
    is the deterministic test path, mirroring ``serve()``.

    Fast paths (each optional, all off by default):

    * ``symbol_gen`` — ``symbol_gen(step_len) -> Symbol`` for the SAME
      model; arms chunked prefill (window S =
      ``prefill_chunk``/``MXNET_SERVE_PREFILL_CHUNK``) so a T-token
      prompt lands in ⌈T/S⌉ dispatches instead of T.
    * ``draft_symbol_gen``/``draft_params`` — a small draft LM (same
      generator signature) arms speculative decoding with
      ``spec_k``/``MXNET_SERVE_SPEC_K`` proposals per verify dispatch.
    * ``prefix_cache_mb`` (or ``MXNET_SERVE_PREFIX_CACHE_MB``) — the
      byte budget for ``submit(prefix_id=...)`` cache-row reuse; pass
      0 to disable the store entirely.
    """
    window_lens = set()
    chunk = default_prefill_chunk() if prefill_chunk is None \
        else int(prefill_chunk)
    if symbol_gen is not None and chunk > 1:
        window_lens.add(chunk)
    k = default_spec_k() if spec_k is None else int(spec_k)
    draft_engine = None
    if draft_symbol_gen is not None:
        if draft_params is None:
            raise MXNetError("serve_decoder: draft_symbol_gen needs "
                             "draft_params")
        if symbol_gen is None:
            raise MXNetError(
                "serve_decoder: speculative decoding needs symbol_gen= "
                "too — the target verifies K proposals in one "
                "step_len=K window dispatch")
        window_lens.add(k)
    engine = DecodeEngine(name, symbol, arg_params, capacity=capacity,
                          ladder=ladder, context=context,
                          compute_dtype=compute_dtype, logger=logger,
                          symbol_gen=symbol_gen, window_lens=window_lens)
    if draft_symbol_gen is not None:
        draft_engine = DecodeEngine(
            name + ".draft", draft_symbol_gen(1), draft_params,
            capacity=engine.capacity, ladder=engine.ladder.sizes,
            context=context, compute_dtype=compute_dtype, logger=logger,
            symbol_gen=draft_symbol_gen, window_lens=window_lens)
    budget = None if prefix_cache_mb is None \
        else int(float(prefix_cache_mb) * (1 << 20))
    store = None
    if budget is None or budget > 0:
        store = PrefixStore(budget_bytes=budget)
        if store.budget_bytes <= 0:
            store = None
    sched = DecodeScheduler(engine, clock=clock, max_queue=max_queue,
                            default_max_new=default_max_new,
                            logger=logger, draft_engine=draft_engine,
                            prefill_chunk=chunk,
                            spec_k=k if draft_engine is not None
                            else None,
                            prefix_store=store)
    if start:
        sched.start()
    return sched
