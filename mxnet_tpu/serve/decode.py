"""Continuous decode batching: iteration-level scheduling over a
slot-pooled KV cache (the Orca-style serving path, ROADMAP 3b).

``InferenceServer`` batches one-shot requests; a KV-cache decoder is a
*sequence* — hundreds of single-token dispatches carrying device state
between them — and serving it one sequence at a time pins decode
throughput at batch 1. This module serves SLOTS sequences through ONE
pinned program per iteration:

* ``DecodeEngine`` — a slot-capacity rung ladder (``MXNET_SERVE_DECODE_
  SLOTS``, default ``1,4,8``) over ``get_decode_symbol(per_slot=True)``
  graphs: every rung is a Module bound at ``(slots, 1)`` sharing ONE
  set of parameter cells (``BucketingModule``/shared_module, exactly
  like the batch bucket ladder) with its own slot-pooled
  ``(slots, H, C, Dh)`` KV-cache aux; ``warmup`` compiles and PINS
  every rung, after which join/leave/rung-switches never mint a trace —
  ``compiles_since_warmup()`` stays 0. Rung switches migrate the live
  slots' cache rows + cursors between rung pools with eager per-row
  copies (no program-cache entries).
* ``DecodeScheduler`` — iteration-level continuous batching on the
  ``submit`` seam: prefill admission into free slots (prompt tokens
  ride the iteration stream, one per dispatch, so the program shape
  never changes), per-iteration retirement (EOS / max-new-tokens /
  deadline / per-slot cache overflow — an overflowing slot fails ALONE,
  batchmates keep decoding), greedy sampling, and streaming token
  delivery through ``DecodeHandle`` callbacks. Two drive modes, same as
  the server: ``start()`` (dispatch thread, real clock) and ``pump()``
  (explicit iterations, FakeClock-deterministic).

Per-sequence traces survive being batched with strangers: every
sequence keeps its own session trace (root span
``serve.decode.sequence``), and each iteration records ONE shared
``serve.decode.step`` span id mirrored into every active sequence's
trace — the same shared-dispatch-span contract batched requests follow.

Telemetry (always on, docs/serving.md has the catalog):
``serve.decode.slots``/``active``/``occupancy``/``queue.depth`` gauges,
``serve.decode.iterations``/``tokens``/``joins``/``leaves``/
``migrations``/``requests``/``responses``/``errors`` counters,
``serve.decode.step.seconds`` + ``serve.decode.request.latency.seconds``
histograms, and one flight-ring record per iteration.
"""
from __future__ import annotations

import itertools
import logging
import os
import threading

import numpy as np

from .. import program_cache as _progcache
from .. import telemetry as _telemetry
from ..telemetry import trace as _trace
from ..base import MXNetError
from ..io import DataDesc
from .batching import BucketLadder, QueueFullError
from .clock import MonotonicClock

__all__ = ["DecodeEngine", "DecodeScheduler", "DecodeHandle",
           "default_slot_ladder", "serve_decoder"]

log = logging.getLogger(__name__)

_seq_ids = itertools.count()


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_slot_ladder():
    """The slot-capacity rung ladder from ``MXNET_SERVE_DECODE_SLOTS``
    (default ``1,4,8``): comma-separated concurrent-sequence capacities,
    sorted ascending, duplicates dropped — the decode-side analog of
    ``MXNET_SERVE_BUCKETS``."""
    raw = os.environ.get("MXNET_SERVE_DECODE_SLOTS", "1,4,8")
    try:
        sizes = sorted({int(tok) for tok in raw.split(",") if tok.strip()})
    except ValueError:
        raise MXNetError(f"MXNET_SERVE_DECODE_SLOTS={raw!r}: expected "
                         "comma-separated slot counts")
    if not sizes or sizes[0] < 1:
        raise MXNetError(f"MXNET_SERVE_DECODE_SLOTS={raw!r}: slot "
                         "counts must be >= 1")
    return sizes


class _Sequence:
    """One admitted decode request's scheduling state."""

    __slots__ = ("id", "prompt", "max_new", "eos_id", "arrival",
                 "deadline", "trace", "root_sid", "handle", "fed",
                 "generated", "slot", "finish_reason")

    def __init__(self, prompt, max_new, eos_id, arrival, deadline,
                 trace=None):
        self.id = next(_seq_ids)
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.arrival = arrival
        self.deadline = deadline          # absolute clock s, or None
        self.trace = trace
        self.root_sid = None
        self.fed = 0                      # prompt+generated tokens fed
        self.generated = []
        self.slot = None
        self.finish_reason = None
        self.handle = DecodeHandle(self)

    def next_token(self):
        """The token this sequence feeds THIS iteration: the next
        prompt token while prefilling, else the last sampled one."""
        if self.fed < len(self.prompt):
            return int(self.prompt[self.fed])
        return int(self.generated[-1])

    def emitting(self):
        """Does this iteration's output row carry a NEW token? True
        once the last prompt token has been fed (its logits predict the
        first generated position)."""
        return self.fed >= len(self.prompt) - 1


class DecodeHandle:
    """Streaming sync+async result surface for one decode request.

    Mirrors ``ResponseHandle`` (``done()``/``result()``/
    ``add_done_callback``/``latency``) and adds the streaming half:
    ``add_token_callback(fn)`` runs ``fn(handle, token, index)`` for
    every generated token — already-emitted tokens replay immediately
    on registration, so a late subscriber misses nothing. ``result()``
    returns the generated ids as an int32 numpy array (EOS excluded);
    ``finish_reason`` is ``"eos"``, ``"length"`` (max-new-tokens),
    ``"deadline"`` (partial result, deadline passed mid-decode), or
    None when the sequence errored (``exception()`` carries it).
    """

    def __init__(self, request):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._done_callbacks = []
        self._token_callbacks = []
        self._tokens = []
        self._error = None
        self.request = request
        self.completed_at = None        # scheduler-clock seconds
        self.first_token_at = None

    def done(self):
        return self._event.is_set()

    @property
    def trace_id(self):
        tr = self.request.trace
        return tr.trace_id if tr is not None else None

    @property
    def tokens(self):
        """Generated token ids so far (list copy — streaming-safe)."""
        with self._lock:
            return list(self._tokens)

    @property
    def finish_reason(self):
        return self.request.finish_reason

    @property
    def latency(self):
        """Admission-to-completion seconds (None until done)."""
        if self.completed_at is None:
            return None
        return self.completed_at - self.request.arrival

    @property
    def ttft(self):
        """Admission-to-first-token seconds (None before the first)."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.request.arrival

    def missed_deadline(self):
        return (self.completed_at is not None
                and self.request.deadline is not None
                and self.completed_at > self.request.deadline)

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise MXNetError(
                f"decode request {self.request.id} not complete within "
                f"{timeout}s (scheduler stopped or stuck?)")
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int32)

    def exception(self):
        return self._error if self._event.is_set() else None

    def add_done_callback(self, fn):
        with self._lock:
            if not self._event.is_set():
                self._done_callbacks.append(fn)
                return
        fn(self)

    def add_token_callback(self, fn):
        """Stream generated tokens: ``fn(handle, token, index)`` per
        token, starting with an immediate replay of any already
        emitted."""
        with self._lock:
            replay = list(enumerate(self._tokens))
            self._token_callbacks.append(fn)
        for i, tok in replay:
            self._safe(fn, tok, i)

    def _safe(self, fn, *args):
        try:
            fn(self, *args)
        except Exception:       # a client callback must not kill the
            pass                # scheduler thread

    def _emit(self, token, now=None):
        with self._lock:
            index = len(self._tokens)
            self._tokens.append(int(token))
            cbs = list(self._token_callbacks)
        if index == 0:
            self.first_token_at = now
        for fn in cbs:
            self._safe(fn, int(token), index)

    def _complete(self, error=None, now=None):
        with self._lock:
            self._error = error
            self.completed_at = now
            callbacks, self._done_callbacks = self._done_callbacks, []
            self._event.set()
        for fn in callbacks:
            self._safe(fn)


class DecodeEngine:
    """Slot-capacity rung ladder over a slot-pooled decode graph.

    ``symbol`` must be a per-slot stateful decode graph (for the LM
    workload: ``models.transformer.get_decode_symbol(per_slot=True)``)
    whose batch dim is the slot count — the SAME symbol binds at every
    rung, so all rungs share one parameter-cell set through the bucket
    leader while each owns its rung-sized KV-cache pool. ``capacity``
    defaults to the bound cache's (inferred from the aux shapes);
    ``pos_embed`` is detected from the graph (a ``pos_ids`` argument =
    learned positions, fed per slot by the drivers).
    """

    def __init__(self, name, symbol, arg_params, aux_params=None,
                 capacity=None, ladder=None, context=None,
                 compute_dtype=None, logger=None):
        from ..context import current_context
        from ..module import BucketingModule

        self.name = name
        self.ladder = ladder if isinstance(ladder, BucketLadder) \
            else BucketLadder(ladder if ladder is not None
                              else default_slot_ladder())
        self.exec_est = {}              # rung -> EMA'd step seconds
        self._warm_mark = None
        self.warmup_compiles = None
        self._symbol = symbol
        self._context = context if context is not None \
            else current_context()
        self.pos_embed = "learned" \
            if "pos_ids" in symbol.list_arguments() else "rotary"
        self.data_names = ("data",) + (
            ("pos_ids",) if self.pos_embed == "learned" else ())
        if not any(getattr(n.opdef(), "stateful_infer", False)
                   for n in symbol._topo_nodes() if not n.is_variable):
            raise MXNetError(
                f"DecodeEngine({name!r}): the symbol has no stateful "
                "decode op (build it with get_decode_symbol("
                "per_slot=True))")

        self._bm = BucketingModule(
            sym_gen=lambda slots: (symbol, list(self.data_names), []),
            default_bucket_key=self.ladder.max,
            logger=logger or log, context=self._context)
        if compute_dtype is not None:
            self._bm._module_kwargs["compute_dtype"] = compute_dtype
        self._bm.bind(self._provide_data(self.ladder.max),
                      label_shapes=None, for_training=False)
        # straight to the leader with initializer=None: the decode
        # graph's aux states (KV cache + cursor) are absent from any
        # trained param set and must stay their bound zeros —
        # BucketingModule.init_params would fall back to Uniform and
        # trip over the cursor's name pattern
        self._bm._leader.init_params(initializer=None,
                                     arg_params=dict(arg_params or {}),
                                     aux_params=dict(aux_params or {}),
                                     allow_missing=True)
        self._bm.params_initialized = True
        self._bm._params_dirty = False
        self._bm.warm_buckets(
            [(s, self._provide_data(s), None) for s in self.ladder])

        if capacity is None:
            exe = self._bm._leader._exec_group.executor
            caches = [cell for nm, cell in exe.aux_dict.items()
                      if nm.endswith("k_cache")]
            if not caches:
                raise MXNetError(f"DecodeEngine({name!r}): no KV-cache "
                                 "aux state in the bound graph")
            capacity = caches[0].shape[2]
        self.capacity = int(capacity)

        from ..models.transformer import BatchedKVCacheDecoder
        self._drivers = {
            s: BatchedKVCacheDecoder(self._bm._buckets[s],
                                     self.capacity, slots=s,
                                     pos_embed=self.pos_embed)
            for s in self.ladder}

    def _provide_data(self, slots):
        descs = [DataDesc("data", (slots, 1), np.int32)]
        if self.pos_embed == "learned":
            descs.append(DataDesc("pos_ids", (slots, 1), np.float32))
        return descs

    def driver(self, rung):
        """The rung's ``BatchedKVCacheDecoder``."""
        return self._drivers[rung]

    # ------------------------------------------------------------- warmup
    def warmup(self, clock):
        """Compile every slot rung (two steps: first pays the trace,
        second measures steady state on ``clock``), pin the programs,
        record the compile delta. Warmup garbage stays harmless: the
        drivers' slots are all free afterwards and a join rewinds the
        slot's cursor."""
        mark = _progcache.compile_count()
        for rung in self.ladder:
            drv = self._drivers[rung]
            zeros = np.zeros((rung, 1), np.int32)
            drv.step(zeros).asnumpy()            # trace + compile
            t0 = clock.now()
            drv.step(zeros).asnumpy()            # steady state
            self.exec_est[rung] = max(0.0, clock.now() - t0)
            drv.active[:] = False
        self._pin_programs()
        self._warm_mark = _progcache.compile_count()
        self.warmup_compiles = self._warm_mark - mark
        return dict(self.exec_est)

    def note_exec(self, rung, seconds):
        prev = self.exec_est.get(rung)
        self.exec_est[rung] = seconds if prev is None else \
            0.7 * prev + 0.3 * seconds

    def exec_estimate(self, rung):
        if rung in self.exec_est:
            return self.exec_est[rung]
        known = list(self.exec_est.values())
        return max(known) if known else 0.0

    def compiles_since_warmup(self):
        if self._warm_mark is None:
            return None
        return _progcache.compile_count() - self._warm_mark

    def program_keys(self):
        keys = []
        for rung, mod in self._bm._buckets.items():
            key = mod._exec_group.executor.program_cache_key("fwd_infer")
            if key is not None:
                keys.append(key)
        return keys

    def _pin_programs(self):
        for key in self.program_keys():
            if not _progcache.pin(key):
                log.warning(
                    "decode %r: rung program not resident at pin time "
                    "(cache capacity too small for the slot ladder? "
                    "MXNET_PROGRAM_CACHE_SIZE)", self.name)

    def programs_resident(self):
        keys = self.program_keys()
        return all(_progcache.contains(k) for k in keys) if keys else True

    # ---------------------------------------------------------- migration
    def migrate(self, src_rung, dst_rung, pairs):
        """Carry live slots between rung pools: for every (src_row,
        dst_row) pair, the slot's cache rows and cursor copy from the
        ``src_rung`` aux arrays into ``dst_rung``'s, and the host
        mirrors follow. Eager per-row gathers/scatters — nothing lands
        in the program cache, so rung switches keep the zero-compile
        contract."""
        if src_rung == dst_rung:
            return
        sdrv, ddrv = self._drivers[src_rung], self._drivers[dst_rung]
        s_exe = self._bm._buckets[src_rung]._exec_group.executor
        d_exe = self._bm._buckets[dst_rung]._exec_group.executor
        ddrv.active[:] = False
        if pairs:
            si = np.asarray([p[0] for p in pairs])
            di = np.asarray([p[1] for p in pairs])
            for nm, cell in s_exe.aux_dict.items():
                dcell = d_exe.aux_dict[nm]
                dcell._set(dcell.asjax().at[di].set(cell.asjax()[si]))
            for s_row, d_row in pairs:
                ddrv.pos[d_row] = sdrv.pos[s_row]
                ddrv.active[d_row] = True
        sdrv.active[:] = False


class DecodeScheduler:
    """Iteration-level continuous batching over one ``DecodeEngine``.

    ``submit(prompt)`` admits a sequence (``QueueFullError`` past
    ``MXNET_SERVE_DECODE_MAX_QUEUE``) and returns a streaming
    ``DecodeHandle``. Each scheduler iteration retires finished
    sequences (EOS / max-new / deadline / per-slot overflow), admits
    queued ones into free slots (growing the rung when the ladder
    allows), migrates live slots on rung switches, then advances every
    slot one token through the rung's pinned program and streams the
    sampled tokens. Greedy (argmax) sampling.
    """

    def __init__(self, engine, clock=None, max_queue=None,
                 default_max_new=None, logger=None):
        self.engine = engine
        self._clock = clock if clock is not None else MonotonicClock()
        self._max_queue = max_queue if max_queue is not None else \
            _env_int("MXNET_SERVE_DECODE_MAX_QUEUE", 256)
        self._default_max_new = default_max_new if default_max_new \
            is not None else _env_int("MXNET_SERVE_DECODE_MAX_NEW", 64)
        self.logger = logger or log
        # reentrant: completion/token callbacks run with the scheduler
        # lock held and may legitimately submit a follow-up sequence
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue = []
        self._rung = self.engine.ladder.sizes[0]
        self._slots = [None] * self._rung
        self._thread = None
        self._running = False
        self.iterations = 0
        self.migrations = 0
        with _telemetry.span("serve.decode.warmup",
                             model=self.engine.name):
            est = self.engine.warmup(self._clock)
        self.logger.info(
            "decode %r warmed — slot ladder %s, %d compiles, step est %s",
            self.engine.name, self.engine.ladder.sizes,
            self.engine.warmup_compiles,
            {r: f"{s * 1e3:.2f}ms" for r, s in est.items()})
        self._gauge("slots").set(self._rung)
        self._gauge("active").set(0)
        self._gauge("occupancy").set(0.0)
        self._gauge("queue.depth").set(0)

    def _gauge(self, key):
        return _telemetry.gauge(f"serve.decode.{key}",
                                model=self.engine.name)

    def _counter(self, key):
        return _telemetry.counter(f"serve.decode.{key}",
                                  model=self.engine.name)

    # ------------------------------------------------------------ admission
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, trace=None):
        """Admit one sequence: ``prompt`` is a 1-D int id sequence
        (1 <= len <= cache capacity). ``max_new_tokens`` caps
        generation (``MXNET_SERVE_DECODE_MAX_NEW`` default); ``eos_id``
        retires the sequence when sampled (not emitted);
        ``deadline_ms`` (relative to now) retires it mid-decode with a
        partial result and ``finish_reason="deadline"``. Returns the
        streaming ``DecodeHandle``."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise MXNetError("empty prompt")
        if prompt.size > self.engine.capacity:
            raise MXNetError(
                f"prompt of {prompt.size} tokens exceeds the decode "
                f"cache capacity {self.engine.capacity}")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self._default_max_new)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        now = self._clock.now()
        deadline = None if deadline_ms is None \
            else now + deadline_ms / 1000.0
        tr = trace
        if tr is None and _trace.sample():
            tr = _trace.new_trace(session=True)
        seq = _Sequence(prompt, max_new, eos_id, now, deadline, trace=tr)
        if tr is not None:
            seq.root_sid = _trace.next_span_id()
            if tr.root is None:
                tr.root = seq.root_sid
            if tr.start_s is None:
                tr.start_s = now
        with self._cond:
            if len(self._queue) >= self._max_queue:
                exc = QueueFullError(
                    f"decode {self.engine.name!r}: queue depth "
                    f"{len(self._queue)} at MXNET_SERVE_DECODE_"
                    f"MAX_QUEUE={self._max_queue}")
                if tr is not None:
                    exc.trace_id = tr.trace_id
                _telemetry.counter("serve.rejected",
                                   model=self.engine.name).inc()
                raise exc
            self._queue.append(seq)
            depth = len(self._queue)
            self._cond.notify_all()
        self._counter("requests").inc()
        self._gauge("queue.depth").set(depth)
        return seq.handle

    # ----------------------------------------------------------- scheduling
    def _active(self):
        return [s for s in self._slots if s is not None]

    def _finish(self, seq, reason=None, error=None, now=None):
        """Complete a sequence's handle and free its slot (caller holds
        the lock)."""
        seq.finish_reason = reason
        if seq.slot is not None:
            self.engine.driver(self._rung).leave(seq.slot)
            self._slots[seq.slot] = None
            seq.slot = None
            self._counter("leaves").inc()
        if seq.trace is not None:
            _trace.record(
                seq.trace, "serve.decode.sequence", seq.arrival,
                now if now is not None else self._clock.now(),
                span_id=seq.root_sid, model=self.engine.name,
                prompt=len(seq.prompt), generated=len(seq.generated),
                finish=reason if error is None else
                type(error).__name__)
            if error is not None:
                error.trace_id = seq.trace.trace_id
        self._counter("errors" if error is not None
                      else "responses").inc()
        if error is None:
            _telemetry.histogram(
                "serve.decode.request.latency.seconds",
                model=self.engine.name).observe(
                max(0.0, (now if now is not None else
                          self._clock.now()) - seq.arrival),
                exemplar=seq.trace.trace_id
                if seq.trace is not None else None)
        seq.handle._complete(error=error, now=now)

    def _switch_rung(self, target):
        """Migrate live slots into the ``target`` rung pool, compacting
        them into the lowest rows (caller holds the lock)."""
        pairs = []
        new_slots = [None] * target
        dst = 0
        for row, seq in enumerate(self._slots):
            if seq is None:
                continue
            pairs.append((row, dst))
            seq.slot = dst
            new_slots[dst] = seq
            dst += 1
        self.engine.migrate(self._rung, target, pairs)
        self._rung = target
        self._slots = new_slots
        self.migrations += 1
        self._counter("migrations").inc()
        self._gauge("slots").set(target)

    def _admit_locked(self, now):
        """Retire expired queued requests, grow the rung if the backlog
        wants it, and fill free slots FIFO."""
        for seq in [s for s in self._queue
                    if s.deadline is not None and now > s.deadline]:
            self._queue.remove(seq)
            self._finish(seq, reason="deadline", now=now)
        if not self._queue:
            return
        want = min(len(self._active()) + len(self._queue),
                   self.engine.ladder.max)
        target = self.engine.ladder.bucket_for(max(want, 1))
        if target is not None and target > self._rung:
            self._switch_rung(target)
        drv = self.engine.driver(self._rung)
        for row in range(self._rung):
            if self._slots[row] is not None or not self._queue:
                continue
            seq = self._queue.pop(0)
            drv.join(row)
            seq.slot = row
            self._slots[row] = seq
            self._counter("joins").inc()
            if seq.trace is not None:
                _trace.record(seq.trace, "serve.decode.queue.wait",
                              seq.arrival, now, parent=seq.root_sid,
                              slot=row)

    def _iterate(self):
        """One scheduling iteration; returns tokens emitted (0 = no
        work was ready)."""
        with self._lock:
            now = self._clock.now()
            # retirement BEFORE dispatch: deadline-expired sequences
            # complete with their partial output; a slot whose next
            # token would overflow its cache slice fails ALONE — the
            # program was never dispatched for it, batchmates continue
            for seq in list(self._active()):
                if seq.deadline is not None and now > seq.deadline:
                    self._finish(seq, reason="deadline", now=now)
            for row in self.engine.driver(self._rung).overflowing():
                seq = self._slots[row]
                if seq is None:          # retired row still advancing
                    continue
                self._finish(seq, error=MXNetError(
                    f"decode {self.engine.name!r}: sequence {seq.id} "
                    f"overflowed its KV-cache slice (slot {row}, "
                    f"capacity {self.engine.capacity}); shorten the "
                    "prompt/max_new_tokens or re-bind with a larger "
                    "capacity"), now=now)
            self._admit_locked(now)
            active = self._active()
            if not active:
                self._gauge("active").set(0)
                self._gauge("occupancy").set(0.0)
                return 0
            # shrink to the smallest rung covering the live set (frees
            # the larger pool's compute for the next iterations)
            target = self.engine.ladder.bucket_for(len(active))
            if target is not None and target < self._rung:
                self._switch_rung(target)
            drv = self.engine.driver(self._rung)
            tokens = np.zeros((self._rung, 1), np.int32)
            for row, seq in enumerate(self._slots):
                if seq is not None:
                    tokens[row, 0] = seq.next_token()
            active = list(self._active())
            shared_sid = _trace.next_span_id() \
                if any(s.trace is not None for s in active) else None
            t0 = now

        # dispatch outside the lock: submits stay non-blocking while
        # the program runs (only pump()/the dispatch thread iterates,
        # so the engine itself needs no second guard)
        logits = drv.step(tokens).asnumpy()       # (rung, 1, V)
        sampled = np.argmax(logits[:, 0, :], axis=-1)

        with self._lock:
            end = self._clock.now()
            step_s = max(0.0, end - t0)
            self.engine.note_exec(self._rung, step_s)
            emitted = 0
            for seq in active:
                if seq.slot is None:
                    continue
                emit = seq.emitting()
                seq.fed += 1
                if seq.trace is not None:
                    _trace.record(
                        seq.trace, "serve.decode.step", t0, end,
                        span_id=shared_sid, parent=seq.root_sid,
                        rung=self._rung, n_active=len(active),
                        shared=True, pos=seq.fed - 1)
                if not emit:
                    continue                      # still prefilling
                tok = int(sampled[seq.slot])
                if seq.eos_id is not None and tok == seq.eos_id:
                    self._finish(seq, reason="eos", now=end)
                    continue                # EOS retires, not emitted
                seq.generated.append(tok)
                seq.handle._emit(tok, now=end)
                emitted += 1
                if len(seq.generated) >= seq.max_new:
                    self._finish(seq, reason="length", now=end)
            self.iterations += 1
            n_active = len(self._active())
            self._counter("iterations").inc()
            if emitted:
                self._counter("tokens").inc(emitted)
            _telemetry.histogram("serve.decode.step.seconds",
                                 model=self.engine.name).observe(step_s)
            self._gauge("active").set(n_active)
            self._gauge("occupancy").set(n_active / self._rung)
            self._gauge("queue.depth").set(len(self._queue))
            compiles = self.engine.compiles_since_warmup()
            _telemetry.gauge(
                "serve.program_cache.compiles_since_warmup").set(
                compiles or 0)
            _telemetry.flightrec.note(
                "serve.decode.step", model=self.engine.name,
                rung=self._rung, active=n_active, emitted=emitted,
                step_us=int(step_s * 1e6),
                compiles_since_warmup=compiles)
        return max(1, emitted)

    # ----------------------------------------------------------- drive modes
    def _has_work(self):
        return bool(self._queue) or any(
            s is not None for s in self._slots)

    def pump(self, max_iterations=None):
        """Deterministic drive: run scheduler iterations until nothing
        is active or queued (or ``max_iterations``). The FakeClock
        path — no thread, no sleeps. Returns iterations run."""
        done = 0
        while max_iterations is None or done < max_iterations:
            with self._lock:
                if not self._has_work():
                    break
            if self._iterate() == 0 and not self._queue:
                break
            done += 1
        return done

    def _loop(self):
        while True:
            with self._cond:
                if not self._running:
                    return
                if not self._has_work():
                    # bounded wait so queued-request deadlines are
                    # noticed; a submit notifies sooner
                    self._cond.wait(timeout=0.05)
                    continue
            self._iterate()

    def start(self):
        """Spawn the decode dispatch thread (idempotent)."""
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="mxnet-serve-decode",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True):
        """Stop the thread; ``drain`` finishes in-flight and queued
        sequences first, else they fail with MXNetError."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        if drain:
            self.pump()
        else:
            with self._lock:
                now = self._clock.now()
                for seq in list(self._active()) + self._queue:
                    self._finish(seq, error=MXNetError(
                        "decode scheduler stopped"), now=now)
                self._queue = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---------------------------------------------------------------- stats
    def stats(self):
        """Snapshot for dashboards/bench: slot occupancy, queue depth,
        token/iteration counters, per-rung step estimates, and the
        zero-compile gate reading."""

        def c(key):
            m = _telemetry.get_metric(f"serve.decode.{key}",
                                      model=self.engine.name)
            return m.value if m is not None else 0

        with self._lock:
            n_active = len(self._active())
            depth = len(self._queue)
            rung = self._rung
        h = _telemetry.get_metric("serve.decode.request.latency.seconds",
                                  model=self.engine.name)
        its = c("iterations")
        return {
            "model": self.engine.name,
            "ladder": self.engine.ladder.sizes,
            "rung": rung,
            "active": n_active,
            "occupancy": round(n_active / rung, 4) if rung else None,
            "queue_depth": depth,
            "requests": c("requests"),
            "responses": c("responses"),
            "errors": c("errors"),
            "iterations": its,
            "tokens": c("tokens"),
            "tokens_per_iteration": round(c("tokens") / its, 3)
            if its else None,
            "joins": c("joins"),
            "leaves": c("leaves"),
            "migrations": c("migrations"),
            "latency_ms": None if h is None or not h.count else {
                "p50": round((h.quantile(0.50) or 0) * 1e3, 3),
                "p99": round((h.quantile(0.99) or 0) * 1e3, 3),
                "mean": round(h.mean * 1e3, 3)},
            "exec_est_ms": {r: round(s * 1e3, 3) for r, s in
                            sorted(self.engine.exec_est.items())},
            "capacity": self.engine.capacity,
            "compiles_since_warmup": self.engine.compiles_since_warmup(),
            "programs_resident": self.engine.programs_resident(),
        }


def serve_decoder(symbol, arg_params, name="decoder", capacity=None,
                  ladder=None, clock=None, start=True, max_queue=None,
                  default_max_new=None, context=None, compute_dtype=None,
                  logger=None):
    """One-call front end for continuous decode batching:
    ``serve_decoder(decode_symbol, params).submit([ids...])``.

    ``symbol`` is a per-slot decode graph
    (``get_decode_symbol(per_slot=True)``); builds the slot-rung
    ``DecodeEngine``, warms+pins every rung, and (by default) starts
    the dispatch thread — ``start=False`` + ``pump()`` with a FakeClock
    is the deterministic test path, mirroring ``serve()``."""
    engine = DecodeEngine(name, symbol, arg_params, capacity=capacity,
                          ladder=ladder, context=context,
                          compute_dtype=compute_dtype, logger=logger)
    sched = DecodeScheduler(engine, clock=clock, max_queue=max_queue,
                            default_max_new=default_max_new,
                            logger=logger)
    if start:
        sched.start()
    return sched
