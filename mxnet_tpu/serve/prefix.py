"""Hashed prefix store over slot-pool KV-cache rows.

Requests that share a system prompt keep re-prefilling it: the cache
rows they'd compute are byte-identical every time. ``PrefixStore`` is
the reuse plane — when a sequence submitted with ``prefix_id=`` finishes
prefilling, the scheduler snapshots its first ``len(prompt)`` cache
positions (every layer's K and V rows, for the target engine and — when
speculative decoding is armed — the draft engine too) plus the token
ids they encode. The next ``submit(prefix_id=...)`` whose prompt starts
with those tokens *joins at cursor C*: the bit-clean slot join writes
the stored rows back and rewinds the cursor to C instead of 0, so the
sequence skips straight past the shared prefix (⌈C/S⌉ dispatches
saved) and its cache is bitwise what a cold prefill would have written.

Contract: one ``prefix_id`` names one token prefix. The store
VALIDATES (stored tokens must equal the new prompt's head) — a
mismatched id counts as a miss (and a ``mismatches`` tick), never a
wrong join. Entries are LRU-evicted under a byte budget
(``MXNET_SERVE_PREFIX_CACHE_MB``, default 64) charged in the static
memory planner (``analysis.memplan``) so ME801 gates HBM with the
store's worst case included.
"""
from __future__ import annotations

import os
from collections import OrderedDict

import numpy as np

__all__ = ["PrefixStore", "default_prefix_budget_bytes"]


def default_prefix_budget_bytes():
    """``MXNET_SERVE_PREFIX_CACHE_MB`` (docs/env_var.md), default 64
    MiB; 0 disables reuse."""
    try:
        mb = float(os.environ.get("MXNET_SERVE_PREFIX_CACHE_MB", "64"))
    except ValueError:
        mb = 64.0
    return int(max(0.0, mb) * (1 << 20))


class _Entry:
    __slots__ = ("tokens", "payloads", "nbytes", "hits")

    def __init__(self, tokens, payloads):
        self.tokens = np.asarray(tokens, np.int64).reshape(-1)
        self.payloads = payloads     # engine tag -> {cell name: rows}
        self.nbytes = self.tokens.nbytes + sum(
            arr.nbytes for rows in payloads.values()
            for arr in rows.values())
        self.hits = 0


class PrefixStore:
    """LRU byte-budgeted map ``prefix_id -> (tokens, cache rows)``."""

    def __init__(self, budget_bytes=None):
        self.budget_bytes = int(budget_bytes
                                if budget_bytes is not None
                                else default_prefix_budget_bytes())
        self._entries = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.mismatches = 0
        self.evictions = 0

    def __len__(self):
        return len(self._entries)

    @property
    def used_bytes(self):
        return sum(e.nbytes for e in self._entries.values())

    def lookup(self, prefix_id, prompt, tags=()):
        """Hit test for one admission: returns ``(C, entry)`` — the
        usable cursor (capped at ``len(prompt) - 1`` so the join always
        has at least one token left to feed, which the first dispatch
        samples from) — or ``(0, None)`` on miss. ``tags`` names the
        engine payloads the caller needs (e.g. the draft engine's rows
        when speculation is armed): an entry missing one is a miss, not
        a half-join."""
        entry = self._entries.get(prefix_id)
        if entry is None:
            self.misses += 1
            return 0, None
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        c = min(entry.tokens.shape[0], prompt.shape[0] - 1)
        if c < 1 or not np.array_equal(entry.tokens[:c], prompt[:c]):
            self.mismatches += 1
            self.misses += 1
            return 0, None
        if any(tag not in entry.payloads for tag in tags):
            self.misses += 1
            return 0, None
        self._entries.move_to_end(prefix_id)
        entry.hits += 1
        self.hits += 1
        return c, entry

    def put(self, prefix_id, tokens, payloads):
        """Store (or refresh) one prefix. Oversized entries are
        dropped whole; otherwise LRU entries evict until the budget
        holds. Returns True when stored."""
        entry = _Entry(tokens, payloads)
        if self.budget_bytes <= 0 or entry.nbytes > self.budget_bytes:
            return False
        self._entries.pop(prefix_id, None)
        while self._entries and \
                self.used_bytes + entry.nbytes > self.budget_bytes:
            self._entries.popitem(last=False)
            self.evictions += 1
        self._entries[prefix_id] = entry
        return True

    def stats(self):
        total = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "bytes": self.used_bytes,
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "mismatches": self.mismatches,
            "evictions": self.evictions,
            "hit_rate": round(self.hits / total, 4) if total else None,
        }
