"""Token sampling for the decode plane: temperature / top-k / top-p
with a recorded per-request rng chain, plus the exact speculative
rejection rule.

Sampling runs HOST-side on the logits row the pinned program already
returned — the device program stays sampling-free, so arming
temperature/top-k/top-p (or switching a request between them) never
mints a program-cache trace. Determinism contract:

* every request owns one ``numpy`` PCG64 chain seeded by
  ``SamplingParams.seed`` — draws happen in a fixed order (draft
  proposals first, then verify, one uniform per decision), and greedy
  decisions consume NO draws (so a greedy run is bit-identical whether
  or not a seed was set);
* the math is float64 end-to-end (softmax, filters, inverse-CDF), so
  replaying the same logits bytes through the same chain reproduces the
  same token bytes on any host;
* ``speculative_verify`` implements the exact rejection rule (accept
  ``d`` with prob ``min(1, p(d)/q(d))``; on reject sample the residual
  ``max(p - q, 0)``), which makes accepted output distributionally
  identical to target-only sampling — and bit-identical under greedy.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["SamplingParams", "token_probs", "sample_from",
           "sample_token", "speculative_verify"]


class SamplingParams:
    """Per-request sampling policy. ``temperature=0`` is greedy-argmax
    (the default — byte-compatible with the pre-sampling scheduler);
    ``top_k``/``top_p`` filter the distribution before the draw.
    ``seed`` seeds the request's rng chain — resubmitting the same
    prompt with the same params replays the same token stream byte for
    byte (the trace-plane replay contract)."""

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=0):
        temperature = float(temperature)
        top_k = int(top_k)
        top_p = float(top_p)
        if temperature < 0.0:
            raise MXNetError(f"temperature {temperature} must be >= 0")
        if top_k < 0:
            raise MXNetError(f"top_k {top_k} must be >= 0 (0 = off)")
        if not 0.0 < top_p <= 1.0:
            raise MXNetError(f"top_p {top_p} must be in (0, 1]")
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = int(seed)

    @property
    def greedy(self):
        return self.temperature == 0.0

    def make_rng(self):
        """The request's recorded rng chain: reseeding reproduces every
        draw in order."""
        return np.random.Generator(np.random.PCG64(self.seed))

    def __repr__(self):
        return (f"SamplingParams(temperature={self.temperature}, "
                f"top_k={self.top_k}, top_p={self.top_p}, "
                f"seed={self.seed})")


def token_probs(logits, params):
    """One logits row -> the f64 token distribution ``params`` samples
    from (greedy -> one-hot at the argmax; otherwise tempered softmax
    with top-k then top-p filtering, renormalized). The speculative
    verifier needs the full vector, not just a draw."""
    logits = np.asarray(logits, np.float64).reshape(-1)
    if params.greedy:
        probs = np.zeros(logits.shape[0], np.float64)
        probs[int(np.argmax(logits))] = 1.0
        return probs
    z = logits / params.temperature
    z -= z.max()
    probs = np.exp(z)
    probs /= probs.sum()
    if params.top_k and params.top_k < probs.shape[0]:
        # keep the k largest; ties at the boundary resolve by index
        # order (np.argsort stable on the negated copy) — deterministic
        keep = np.argsort(-probs, kind="stable")[:params.top_k]
        mask = np.zeros(probs.shape[0], bool)
        mask[keep] = True
        probs = np.where(mask, probs, 0.0)
        probs /= probs.sum()
    if params.top_p < 1.0:
        order = np.argsort(-probs, kind="stable")
        csum = np.cumsum(probs[order])
        # minimal prefix reaching top_p mass (>= keeps at least one)
        cut = int(np.searchsorted(csum, params.top_p, side="left")) + 1
        mask = np.zeros(probs.shape[0], bool)
        mask[order[:cut]] = True
        probs = np.where(mask, probs, 0.0)
        probs /= probs.sum()
    return probs


def sample_from(probs, u):
    """Inverse-CDF draw: one uniform ``u`` in [0, 1) against an
    (unnormalized-ok) f64 weight vector."""
    cdf = np.cumsum(np.asarray(probs, np.float64))
    total = cdf[-1]
    if total <= 0.0:
        raise MXNetError("sample_from: all-zero weight vector")
    return int(min(np.searchsorted(cdf, u * total, side="right"),
                   cdf.shape[0] - 1))


def sample_token(logits, params, rng):
    """Sample one token from a logits row. Greedy consumes no rng
    draw; everything else consumes exactly one uniform."""
    if params.greedy:
        return int(np.argmax(np.asarray(logits)))
    return sample_from(token_probs(logits, params), rng.random())


def speculative_verify(target_rows, draft_rows, proposals, params, rng):
    """Exact rejection sampling over one slot's K draft proposals.

    ``target_rows``/``draft_rows`` are (K, V) LOGITS: row ``j`` is the
    distribution for the stream position proposal ``j`` fills (target
    row ``j`` came out of the S=K verify dispatch, draft row ``j`` out
    of proposal dispatch ``j``). Returns ``(accepted, tokens)`` where
    ``accepted`` counts proposals kept and ``tokens`` is what the slot
    commits this iteration: the accepted prefix, plus — when a proposal
    was rejected — one token sampled from the residual
    ``max(p - q, 0)`` (so 1 <= len(tokens) <= K always, and every
    emitted token has nonzero target probability). Under greedy this
    degenerates to: accept while draft and target argmaxes agree, then
    emit the target argmax — bit-identical to target-only decode."""
    proposals = [int(d) for d in proposals]
    emitted = []
    for j, d in enumerate(proposals):
        p = token_probs(target_rows[j], params)
        q = token_probs(draft_rows[j], params)
        pd, qd = float(p[d]), float(q[d])
        if params.greedy:
            accept = pd > 0.0               # one-hot match, no draw
        elif qd <= 0.0:
            # the draft could not have proposed d with q(d)=0 unless
            # filters diverged; accept only if the target admits it
            accept = pd > 0.0
        else:
            accept = rng.random() < min(1.0, pd / qd)
        if accept:
            emitted.append(d)
            continue
        resid = np.maximum(p - q, 0.0)
        if resid.sum() <= 0.0:
            resid = p                        # degenerate: q covers p
        if params.greedy:
            emitted.append(int(np.argmax(resid)))
        else:
            emitted.append(sample_from(resid, rng.random()))
        return j, emitted
    return len(proposals), emitted
