"""Dynamic-batch assembly: bucket ladder, pad/slice, admission queue.

The shape discipline: a request carries one or more *rows* (examples) —
its inputs have a leading row dimension. The batcher coalesces queued
requests FIFO into one batch of N total rows, pads it up to the
smallest ladder bucket B >= N (``pad_rows``: zero rows appended, which
is compute waste but never numerics — every op downstream of the data
input is row-independent in inference mode), runs the pre-compiled
bucket-B program, and slices rows back per request (``slice_rows``).
The pad/slice pair is bit-transparent: row i of the padded batch's
output is exactly the program's output for row i, so a served response
is bitwise-equal to a direct forward of the same rows through the same
bucket program (tests/test_serve.py pins this).

``AdmissionQueue`` owns the per-model FIFO plus the deadline
bookkeeping the scheduler's flush decision reads: a request is admitted
with ``deadline = arrival + deadline_s`` and the queue exposes
``flush_at(exec_est)`` — the latest moment dispatch can start and still
meet the earliest queued deadline given the bucket's measured execution
time. Waiting past ``flush_at`` in the hope of filling a larger bucket
is the pad-vs-wait break-even the scheduler never crosses.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading

import numpy as np

from ..base import MXNetError

__all__ = ["QueueFullError", "ShedError", "BucketLadder",
           "default_ladder", "bucket_for", "pad_rows", "slice_rows",
           "Request", "ResponseHandle", "AdmissionQueue"]

_req_ids = itertools.count()


class QueueFullError(MXNetError):
    """Admission rejected: the model's queue is at MXNET_SERVE_MAX_QUEUE.

    ``retry_after_ms`` (set by the server at raise time) is the
    backpressure hint: the estimated time to drain the current queue,
    from the scheduler's exec-time EMA and the queue depth — a client
    that retries sooner will very likely be rejected again.
    """

    retry_after_ms = None
    trace_id = None


class ShedError(MXNetError):
    """An ADMITTED request was dropped by load shedding: queue depth
    crossed the watermark and this request could no longer meet its
    deadline even if dispatched immediately (already doomed — serving
    it would only waste a bucket slot another request could use).
    Counted under ``serve.shed``, distinct from ``serve.rejected``
    (admission-time rejections). ``trace_id`` names the shed request's
    trace, whose root span carries the queue state that doomed it."""

    retry_after_ms = None
    trace_id = None


def default_ladder():
    """The bucket ladder from ``MXNET_SERVE_BUCKETS`` (default
    ``1,2,4,8,16,32``): comma-separated batch sizes, sorted ascending,
    duplicates dropped."""
    raw = os.environ.get("MXNET_SERVE_BUCKETS", "1,2,4,8,16,32")
    try:
        sizes = sorted({int(tok) for tok in raw.split(",") if tok.strip()})
    except ValueError:
        raise MXNetError(f"MXNET_SERVE_BUCKETS={raw!r}: expected "
                         "comma-separated batch sizes")
    if not sizes or sizes[0] < 1:
        raise MXNetError(f"MXNET_SERVE_BUCKETS={raw!r}: bucket sizes "
                         "must be >= 1")
    return sizes


class BucketLadder:
    """Sorted batch-size rungs one model serves at."""

    def __init__(self, sizes=None):
        sizes = list(sizes) if sizes is not None else default_ladder()
        if not sizes:
            raise MXNetError("empty bucket ladder")
        self.sizes = sorted({int(s) for s in sizes})
        if self.sizes[0] < 1:
            raise MXNetError("bucket sizes must be >= 1")

    @property
    def max(self):
        return self.sizes[-1]

    def bucket_for(self, rows):
        """Smallest rung >= rows (the pad target), or None past the top."""
        for s in self.sizes:
            if s >= rows:
                return s
        return None

    def __iter__(self):
        return iter(self.sizes)

    def __repr__(self):
        return f"BucketLadder({self.sizes})"


def bucket_for(rows, ladder):
    """Module-level convenience over ``BucketLadder.bucket_for``."""
    ladder = ladder if isinstance(ladder, BucketLadder) \
        else BucketLadder(ladder)
    return ladder.bucket_for(rows)


def pad_rows(arr, bucket):
    """Pad ``arr`` (rows leading) with zero rows up to ``bucket``.

    numpy in, numpy out — batch assembly happens host-side; one
    device_put of the assembled batch follows (the engine's single
    host->device transfer per dispatch).
    """
    arr = np.asarray(arr)
    rows = arr.shape[0]
    if rows > bucket:
        raise MXNetError(f"{rows} rows cannot pad down to bucket {bucket}")
    if rows == bucket:
        return arr
    pad = np.zeros((bucket - rows,) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def slice_rows(outputs, start, rows):
    """Rows ``[start, start+rows)`` of every output — the response path
    that undoes the batch/pad. Accepts NDArray or jax/numpy arrays and
    returns NDArrays."""
    from ..ndarray import NDArray
    out = []
    for o in outputs:
        val = o.asjax() if isinstance(o, NDArray) else o
        out.append(NDArray(val[start:start + rows]))
    return out


class Request:
    """One admitted unit of work: inputs (name -> rows-leading numpy
    array), row count, arrival/deadline in scheduler-clock seconds.

    ``trace``/``root_sid``: the request's trace identity when sampled
    (telemetry.trace) — every scheduling stage it crosses records a
    span under ``root_sid`` so the request reconstructs to one span
    tree. A decode-session request shares the session's trace and its
    root span becomes a child of the session root.
    """

    __slots__ = ("id", "model", "inputs", "rows", "arrival", "deadline",
                 "handle", "trace", "root_sid")

    def __init__(self, model, inputs, rows, arrival, deadline,
                 trace=None):
        self.id = next(_req_ids)
        self.model = model
        self.inputs = inputs
        self.rows = rows
        self.arrival = arrival
        self.deadline = deadline
        self.trace = trace
        self.root_sid = None
        self.handle = ResponseHandle(self)


class ResponseHandle:
    """Thread-safe sync+async result surface for one request.

    Sync: ``result(timeout)`` blocks until the dispatch thread (or a
    ``pump()`` call) completes the request, returning the sliced output
    NDArrays or raising the dispatch error. Async: ``done()`` polls,
    ``add_done_callback(fn)`` runs ``fn(handle)`` at completion (or
    immediately if already complete) on the completing thread.
    ``latency``/``bucket``/``completed_at`` carry the telemetry facts
    the load generator aggregates.
    """

    def __init__(self, request):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._callbacks = []
        self._outputs = None
        self._error = None
        self.request = request
        self.bucket = None          # set at dispatch
        self.completed_at = None    # scheduler-clock seconds

    def done(self):
        return self._event.is_set()

    @property
    def trace_id(self):
        """The request's trace id (None when sampling skipped it) — the
        key into ``telemetry.trace.tree()`` for its span tree."""
        tr = self.request.trace
        return tr.trace_id if tr is not None else None

    @property
    def latency(self):
        """Admission-to-completion seconds (None until done)."""
        if self.completed_at is None:
            return None
        return self.completed_at - self.request.arrival

    def missed_deadline(self):
        return (self.completed_at is not None
                and self.completed_at > self.request.deadline)

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise MXNetError(
                f"request {self.request.id} not complete within "
                f"{timeout}s (queue stuck or server stopped?)")
        if self._error is not None:
            raise self._error
        return self._outputs

    def exception(self):
        return self._error if self._event.is_set() else None

    def add_done_callback(self, fn):
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _complete(self, outputs=None, error=None, bucket=None, now=None):
        with self._lock:
            self._outputs = outputs
            self._error = error
            self.bucket = bucket
            self.completed_at = now
            callbacks, self._callbacks = self._callbacks, []
            self._event.set()
        for fn in callbacks:
            try:
                fn(self)
            except Exception:       # a client callback must not kill
                pass                # the dispatch thread


class AdmissionQueue:
    """Per-model FIFO with the scheduler's flush bookkeeping.

    Not self-locking: the owning scheduler serializes access under its
    own lock (admission, flush decisions and batch draining must be one
    atomic step against each other).
    """

    def __init__(self, model, max_requests):
        self.model = model
        self.max_requests = max_requests
        self._q = collections.deque()
        self.rows_pending = 0

    def __len__(self):
        return len(self._q)

    def admit(self, request):
        if len(self._q) >= self.max_requests:
            raise QueueFullError(
                f"model {self.model!r}: queue depth {len(self._q)} at "
                f"MXNET_SERVE_MAX_QUEUE={self.max_requests}")
        self._q.append(request)
        self.rows_pending += request.rows

    def oldest_deadline(self):
        """Earliest deadline among queued requests (FIFO admission with
        one default deadline keeps the head earliest; min() stays
        correct for mixed per-request deadlines)."""
        if not self._q:
            return None
        return min(r.deadline for r in self._q)

    def flush_at(self, exec_est):
        """Latest dispatch start that still meets the earliest queued
        deadline, given ``exec_est`` seconds of bucket execution. The
        scheduler dispatches at this instant rather than keep waiting
        for a larger bucket — the pad-vs-wait break-even."""
        d = self.oldest_deadline()
        return None if d is None else d - exec_est

    def shed_doomed(self, now, exec_est_fn):
        """Remove and return every queued request that cannot meet its
        deadline even if dispatched right now (``deadline < now +
        exec_est_fn(rows)``) — the load-shedding pass the server runs
        when depth crosses the shed watermark. Shedding the doomed
        first protects requests that can still make their SLO: the
        deadline-class ordering the ISSUE names."""
        doomed, keep = [], collections.deque()
        for r in self._q:
            if r.deadline < now + exec_est_fn(r.rows):
                doomed.append(r)
                self.rows_pending -= r.rows
            else:
                keep.append(r)
        self._q = keep
        return doomed

    def drain(self, max_rows):
        """Pop FIFO-prefix requests whose rows fit in ``max_rows``."""
        took, rows = [], 0
        while self._q and rows + self._q[0].rows <= max_rows:
            r = self._q.popleft()
            rows += r.rows
            took.append(r)
        self.rows_pending -= rows
        return took, rows

    def fail_all(self, error, now=None):
        """Complete every queued request with ``error`` (server stop)."""
        while self._q:
            r = self._q.popleft()
            self.rows_pending -= r.rows
            r.handle._complete(error=error, now=now)
