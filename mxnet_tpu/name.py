"""Automatic symbol naming (reference: python/mxnet/name.py NameManager)."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Assigns auto names like ``convolution0`` to anonymous symbols."""

    _local = threading.local()

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        hint = hint.lower()
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        if not hasattr(NameManager._local, "stack"):
            NameManager._local.stack = []
        NameManager._local.stack.append(self)
        return self

    def __exit__(self, *args):
        NameManager._local.stack.pop()


class Prefix(NameManager):
    """Prepends a prefix to every auto name. reference: name.py Prefix."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


_DEFAULT = NameManager()


def current():
    stack = getattr(NameManager._local, "stack", None)
    if stack:
        return stack[-1]
    return _DEFAULT
