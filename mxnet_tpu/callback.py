"""Training callbacks.

API parity with reference python/mxnet/callback.py (batch-end callbacks
receive a ``BatchEndParam``-shaped namedtuple with ``epoch``, ``nbatch``,
``eval_metric``, ``locals``; epoch-end checkpointers receive
``(epoch, symbol, arg_params, aux_params)``), rebuilt around a small
formatting helper instead of the reference's per-callback string plumbing.
"""
from __future__ import annotations

import logging
import time

from . import telemetry as _telemetry

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "module_checkpoint", "ProgressBar",
           "LogValidationMetricsCallback"]


log = logging.getLogger(__name__)


class LogValidationMetricsCallback:
    """Log eval metrics at epoch end (reference: callback.py:159-167)."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            log.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                     value)


def _metric_text(eval_metric, reset=False):
    """'name=val name2=val2' for a metric (possibly composite), or ''."""
    if eval_metric is None:
        return ""
    pairs = eval_metric.get_name_value()
    if reset:
        eval_metric.reset()
    return " ".join(f"{n}={v:f}" for n, v in pairs)


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving symbol + params every ``period`` epochs.

    reference: callback.py:39 (used as ``fit(epoch_end_callback=...)``).
    """
    from .model import save_checkpoint
    period = max(1, int(period))

    def _save(epoch, sym, arg_params, aux_params):
        if (epoch + 1) % period == 0:
            save_checkpoint(prefix, epoch + 1, sym, arg_params, aux_params)
    return _save


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback delegating to ``Module.save_checkpoint`` (so
    optimizer state rides along). reference: callback.py:20."""
    period = max(1, int(period))

    def _save(epoch, sym=None, arg_params=None, aux_params=None):
        if (epoch + 1) % period == 0:
            mod.save_checkpoint(prefix, epoch + 1, save_optimizer_states)
    return _save


def log_train_metric(period, auto_reset=False):
    """Batch-end callback printing the running train metric every
    ``period`` batches. reference: callback.py:60."""
    def _log(param):
        if param.nbatch % period == 0:
            text = _metric_text(param.eval_metric, reset=auto_reset)
            if text:
                log.info("epoch %d batch %d train: %s",
                         param.epoch, param.nbatch, text)
    return _log


class Speedometer:
    """Batch-end callback reporting throughput (samples/sec) and the
    training metric every ``frequent`` batches. reference: callback.py:85.

    Throughput is measured over the window since the previous report, so
    the first report of each epoch is skipped (no window yet).
    """

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._window_start = None
        self._prev_nbatch = 0

    def __call__(self, param):
        if param.nbatch < self._prev_nbatch:  # new epoch: restart window
            self._window_start = None
        self._prev_nbatch = param.nbatch

        if self._window_start is None:
            self._window_start = time.time()
            return
        if param.nbatch % self.frequent != 0:
            return
        elapsed = time.time() - self._window_start
        speed = self.frequent * self.batch_size / max(elapsed, 1e-12)
        text = _metric_text(param.eval_metric, reset=True)
        log.info("Epoch[%d] Batch[%d] speed=%.2f samples/s%s",
                 param.epoch, param.nbatch, speed,
                 " " + text if text else "")
        # telemetry registry sees the same reading the log line carries,
        # so one snapshot()/jsonl dump holds the whole training step
        if _telemetry.enabled():
            _telemetry.gauge("speedometer.samples_per_sec").set(speed)
            _telemetry.histogram(
                "speedometer.samples_per_sec.hist",
                buckets=(10, 100, 1e3, 1e4, 1e5, 1e6, 1e7)).observe(speed)
            _telemetry.record_event("speed", epoch=param.epoch,
                                    nbatch=param.nbatch,
                                    samples_per_sec=speed)
        self._window_start = time.time()


class ProgressBar:
    """Batch-end callback drawing a text progress bar over ``total``
    batches. reference: callback.py:130."""

    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        frac = min(param.nbatch / float(self.total), 1.0)
        filled = int(round(self.length * frac))
        bar = "#" * filled + "." * (self.length - filled)
        log.info("[%s] %d%%", bar, int(100 * frac))
