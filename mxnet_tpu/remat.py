"""Rematerialization (checkpoint) policy for the fused/K-step programs.

The fused train step (executor_group.setup_fused_step) differentiates
the whole forward with ``jax.vjp``, so every intermediate the backward
needs is *saved* between the forward and backward halves of the one XLA
program — the classic activation-memory bill. ZeRO and the memory
accountant freed HBM elsewhere; this knob converts that headroom into
larger batches by shrinking the saved-residual set:

* ``none`` — no rematerialization (the default; programs are identical
  to the pre-knob framework, bit for bit);
* ``dots`` — ``jax.checkpoint`` with the ``dots_saveable`` policy: the
  matmul/conv outputs stay saved (recomputing them would re-pay MXU
  time), everything elementwise between them — BN normalize chains,
  activations, dropout masks — is recomputed during backward from the
  saved dot outputs. The usual sweet spot: memory-bound intermediates
  vanish from the residual set at near-zero recompute FLOPs;
* ``all`` — full rematerialization: only the program *inputs* are
  saved and the whole forward replays inside the backward (~1/3 extra
  FLOPs for convnets, maximum residual savings).

Selection: ``Module.fit(remat="dots")`` > ``MXNET_REMAT_POLICY`` env >
``"none"``. The active policy is part of every program-cache key the
fused/scan steps mint AND of the kernel-tier autotune key (a kernel
measured under ``none`` may lose under ``all``, where its recompute
runs twice — a persisted selection must never leak across policies).

The policy also arms **donation of the step's eval-only
intermediates**: the rng key chain and (when the graph's training
forward refreshes every aux entry — BatchNorm does) the aux-state
buffers are donated to the fused program, since both are replaced by
same-shaped outputs each step and nothing outside the step reads the
stale buffer afterwards. Under ``none`` the donation set stays exactly
the pre-knob (params, optimizer states) so existing bindings are
untouched.

``residual_bytes`` measures what the policy actually buys: the total
bytes of the VJP residual set at trace time (``jax.eval_shape`` over
``jax.vjp`` — no execution, backend-independent). The memory accountant
uses it to gate that a policy drops peak live bytes enough to admit the
next-larger batch bucket (``telemetry.memory.batch_headroom``).
"""
from __future__ import annotations

import os

__all__ = ["POLICIES", "DOT_SAVEABLE_OPS", "resolve", "active",
           "set_active", "wrap", "residual_bytes"]

POLICIES = ("none", "dots", "all")

#: static mirror of ``jax.checkpoint_policies.dots_saveable`` at the op
#: level: ops whose outputs come off the MXU (dot_general / conv
#: primitives) and therefore STAY SAVED under the ``dots`` policy while
#: everything elementwise between them is recomputed. The static memory
#: planner (analysis/memplan.py) folds output bytes of exactly these
#: ops to predict the ``dots`` residual set without tracing; keep the
#: set in sync with the saveable primitives when jax's policy changes.
DOT_SAVEABLE_OPS = frozenset({
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "FusedConvBNReLU", "QuantizedFullyConnected", "QuantizedConvolution",
    "RNN", "attention", "pallas_flash_attention",
})

_override = None        # fit(remat=...) pins the process-wide policy


def _env_policy():
    p = os.environ.get("MXNET_REMAT_POLICY", "none").lower()
    return p if p in POLICIES else "none"


def resolve(explicit=None):
    """Validate + resolve one policy request: explicit > env > none."""
    if explicit is None:
        return active()
    p = str(explicit).lower()
    if p not in POLICIES:
        raise ValueError(
            f"remat policy {explicit!r}: expected one of {POLICIES}")
    return p


def active():
    """The process-wide policy (cache-key token): the ``fit(remat=...)``
    override when one was set, else ``MXNET_REMAT_POLICY``."""
    return _override if _override is not None else _env_policy()


def set_active(policy):
    """Pin the process-wide policy (``None`` returns to env-driven)."""
    global _override
    _override = None if policy is None else resolve(policy)
    return active()


def wrap(f, policy):
    """Apply one policy to a differentiable callable (the fused step's
    forward closure). ``none`` is the identity — the traced program is
    unchanged down to the jaxpr."""
    import jax
    if policy == "dots":
        return jax.checkpoint(
            f, policy=jax.checkpoint_policies.dots_saveable)
    if policy == "all":
        return jax.checkpoint(f)
    return f


def residual_bytes(f, *args):
    """Bytes of the VJP residual set of ``f`` at ``args`` — the
    activations stored between the forward and backward halves, the
    quantity a remat policy shrinks. Pure trace (``jax.eval_shape``):
    nothing executes, so the number is exact and backend-independent.
    """
    import jax

    def res(*a):
        _out, vjp_fn = jax.vjp(f, *a)
        return vjp_fn            # a pytree whose leaves ARE the residuals

    tree = jax.eval_shape(res, *args)
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * leaf.dtype.itemsize
    return total
