"""Network visualization (reference: python/mxnet/visualization.py)."""
from __future__ import annotations

import json

from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64,
                                                                  .74, 1.)):
    """Layer-by-layer summary table. reference: visualization.py:21."""
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
                    if show_shape:
                        key = input_name + "_output" \
                            if input_node["op"] != "null" else input_name
                        if key in shape_dict:
                            pre_filter = pre_filter + int(
                                shape_dict[key][1]
                                if len(shape_dict[key]) > 1 else 0)
        cur_param = 0
        attrs = node.get("attrs", {})
        if op == "Convolution":
            num_filter = int(attrs["num_filter"])
            import ast
            kernel = ast.literal_eval(attrs["kernel"])
            num_group = int(attrs.get("num_group", "1"))
            cur_param = pre_filter * num_filter // num_group
            for k in kernel:
                cur_param *= k
            cur_param += num_filter
        elif op == "FullyConnected":
            if attrs.get("no_bias", "False") == "True":
                cur_param = pre_filter * int(attrs["num_hidden"])
            else:
                cur_param = (pre_filter + 1) * int(attrs["num_hidden"])
        elif op == "BatchNorm":
            key = node["name"] + "_output"
            if show_shape and key in shape_dict:
                cur_param = int(shape_dict[key][1]) * 4
        first_connection = "" if not pre_node else pre_node[0]
        fields = [f"{node['name']}({op})",
                  "x".join([str(x) for x in out_shape]),
                  cur_param, first_connection]
        print_row(fields, positions)
        if len(pre_node) > 1:
            for i in range(1, len(pre_node)):
                fields = ["", "", "", pre_node[i]]
                print_row(fields, positions)
        return cur_param

    heads = set(conf["arg_nodes"])
    for i, node in enumerate(nodes):
        out_shape = []
        op = node["op"]
        if op == "null" and i > 0:
            continue
        if op != "null" or i in heads:
            if show_shape:
                key = node["name"] + "_output" if op != "null" \
                    else node["name"]
                if key in shape_dict:
                    out_shape = shape_dict[key][1:]
        total_params[0] += print_layer_summary(node, out_shape)
        if i == len(nodes) - 1:
            print("=" * line_length)
        else:
            print("_" * line_length)
    print(f"Total params: {total_params[0]}")
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering. reference: visualization.py:150. Gated on the
    graphviz package being available."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("Draw network requires graphviz library")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    node_attrs = node_attrs or {}
    draw_shape = False
    shape_dict = {}
    if shape is not None:
        draw_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    cm = ("#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3", "#fdb462",
          "#b3de69", "#fccde5")

    hidden_nodes = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        attrs = {"shape": "box", "fixedsize": "false", "style": "filled"}
        label = name
        if op == "null":
            if name.endswith("weight") or name.endswith("bias") or \
                    name.endswith("gamma") or name.endswith("beta") or \
                    name.endswith("moving_mean") or \
                    name.endswith("moving_var"):
                if hide_weights:
                    hidden_nodes.add(name)
                continue
            attrs["fillcolor"] = cm[0]
        elif op == "Convolution":
            import ast
            a = node.get("attrs", {})
            label = "Convolution\n{kernel}/{stride}, {filter}".format(
                kernel="x".join(map(str, ast.literal_eval(a["kernel"]))),
                stride="x".join(map(str, ast.literal_eval(
                    a.get("stride", "(1,1)")))),
                filter=a["num_filter"])
            attrs["fillcolor"] = cm[1]
        elif op == "FullyConnected":
            label = f"FullyConnected\n{node['attrs']['num_hidden']}"
            attrs["fillcolor"] = cm[1]
        elif op == "BatchNorm":
            attrs["fillcolor"] = cm[3]
        elif op == "Activation" or op == "LeakyReLU":
            label = f"{op}\n{node.get('attrs', {}).get('act_type', '')}"
            attrs["fillcolor"] = cm[2]
        elif op == "Pooling":
            import ast
            a = node.get("attrs", {})
            label = "Pooling\n{pooltype}, {kernel}/{stride}".format(
                pooltype=a.get("pool_type", "max"),
                kernel="x".join(map(str, ast.literal_eval(
                    a.get("kernel", "(1,1)")))),
                stride="x".join(map(str, ast.literal_eval(
                    a.get("stride", "(1,1)")))))
            attrs["fillcolor"] = cm[4]
        elif op in ("Concat", "Flatten", "Reshape"):
            attrs["fillcolor"] = cm[5]
        elif op == "Softmax" or op == "SoftmaxOutput":
            attrs["fillcolor"] = cm[6]
        else:
            attrs["fillcolor"] = cm[7]
        dot.node(name=name, label=label, **attrs)

    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        inputs = node["inputs"]
        for item in inputs:
            input_node = nodes[item[0]]
            input_name = input_node["name"]
            if input_name not in hidden_nodes:
                attrs = {"dir": "back", "arrowtail": "open"}
                if draw_shape:
                    key = input_name + "_output" \
                        if input_node["op"] != "null" else input_name
                    if key in shape_dict:
                        shape_ = shape_dict[key]
                        label = "x".join([str(x) for x in shape_[1:]])
                        attrs["label"] = label
                dot.edge(tail_name=name, head_name=input_name, **attrs)
    return dot
