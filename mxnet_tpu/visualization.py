"""Network visualization (reference surface: python/mxnet/visualization.py
— print_summary + plot_network).

Implementation walks this framework's native node graph
(``Symbol._topo_nodes``) instead of re-parsing JSON: node attrs are
already typed, and parameter counts come from the shape-inference pass
itself — every op's learnable-input sizes are summed exactly, rather than
re-deriving Conv/FC formulas per op type.
"""
from __future__ import annotations

from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]

def _is_param(name):
    return name.rsplit("_", 1)[-1] in ("weight", "bias", "gamma",
                                       "beta") or \
        name.endswith(("moving_mean", "moving_var"))


def _graph_info(symbol, shape):
    """Per-node rows: (node, out_shape|None, param_count, input_names).
    Non-parameter variables (the graph inputs, e.g. ``data``) get their
    own rows so the summary starts at the network input like the
    reference's table."""
    arg_shape_of = out_shape_of = None
    if shape is not None:
        internals = symbol.get_internals()
        arg_shapes, out_shapes, aux_shapes = internals.infer_shape(**shape)
        names = internals.list_outputs()
        out_shape_of = dict(zip(names, out_shapes))
        arg_shape_of = dict(zip(symbol.list_arguments(), arg_shapes))
        arg_shape_of.update(zip(symbol.list_auxiliary_states(), aux_shapes))
    rows = []
    for node in symbol._topo_nodes():
        if node.is_variable:
            if not _is_param(node.name):
                shp = arg_shape_of.get(node.name) if arg_shape_of else None
                rows.append((node, shp, 0, []))
            continue
        params = 0
        inputs = []
        for inp, _ in node.inputs:
            if inp.is_variable and _is_param(inp.name):
                if shape is not None and inp.name in arg_shape_of:
                    n = 1
                    for d in arg_shape_of[inp.name]:
                        n *= d
                    params += n
            else:
                inputs.append(inp.name)
        out = None
        if shape is not None:
            out = out_shape_of.get(f"{node.name}_output")
            if out is None:  # multi-output ops expose indexed names
                out = out_shape_of.get(f"{node.name}_output0")
        rows.append((node, out, params, inputs))
    return rows


def print_summary(symbol, shape=None, line_length=98,
                  positions=(0.42, 0.66, 0.80, 1.0)):
    """Layer table: name(op) / output shape / #params / feeds-from.
    reference surface: visualization.py print_summary."""
    # fractional positions scale with line_length; absolute column stops
    # (reference calling convention) pass through unchanged
    if positions[-1] <= 1:
        cols = [int(line_length * p) for p in positions]
    else:
        cols = [int(p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def emit(fields):
        line = ""
        for text, stop in zip(fields, cols):
            line = (line + str(text))[:stop].ljust(stop)
        print(line)

    print("=" * line_length)
    emit(header)
    print("=" * line_length)
    total = 0
    rows = _graph_info(symbol, shape)
    for node, out, params, inputs in rows:
        total += params
        shape_txt = "x".join(str(d) for d in out[1:]) if out else ""
        emit([f"{node.name} ({node.op})", shape_txt, params,
              inputs[0] if inputs else ""])
        for extra in inputs[1:]:
            emit(["", "", "", extra])
        print("-" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


_FILL = {
    "input": "#8dd3c7", "compute": "#fb8072", "act": "#ffffb3",
    "norm": "#bebada", "pool": "#80b1d3", "shape": "#fdb462",
    "loss": "#b3de69", "other": "#fccde5",
}


def _node_style(node):
    op = node.op
    attrs = node.attrs
    if op == "Convolution":
        k = "x".join(str(v) for v in attrs.get("kernel", ()))
        return f"Convolution {k}\nfilters={attrs.get('num_filter')}", \
            _FILL["compute"]
    if op == "FullyConnected":
        return f"FullyConnected\n{attrs.get('num_hidden')}", \
            _FILL["compute"]
    if op == "Pooling":
        k = "x".join(str(v) for v in attrs.get("kernel", ()))
        return f"Pooling {attrs.get('pool_type', 'max')}\n{k}", \
            _FILL["pool"]
    if op in ("Activation", "LeakyReLU", "SoftmaxActivation"):
        return f"{op}\n{attrs.get('act_type', '')}", _FILL["act"]
    if op in ("BatchNorm", "InstanceNorm", "L2Normalization", "LRN"):
        return op, _FILL["norm"]
    if op in ("Concat", "Flatten", "Reshape", "SliceChannel", "transpose"):
        return op, _FILL["shape"]
    if node.opdef().is_loss:
        return op, _FILL["loss"]
    return op, _FILL["other"]


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz diagram of the symbol graph. reference surface:
    visualization.py plot_network (requires the graphviz package)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz package")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    rows = _graph_info(symbol, shape)
    edge_shape = {node.name: out for node, out, _, _ in rows}  # vars incl.
    dot = Digraph(name=title, format=save_format)
    base = {"shape": "box", "style": "filled", "fixedsize": "false"}
    base.update(node_attrs or {})
    # user node_attrs win over per-op styling (fillcolor/label included)
    fill_override = base.pop("fillcolor", None)
    label_override = base.pop("label", None)

    shown = set()
    for node in symbol._topo_nodes():
        if node.is_variable:
            if hide_weights and _is_param(node.name):
                continue
            dot.node(node.name, label=label_override or node.name,
                     fillcolor=fill_override or _FILL["input"], **base)
            shown.add(node.name)
            continue
        label, fill = _node_style(node)
        if "\n" not in label:
            label = f"{node.name}\n{label}"
        dot.node(node.name, label=label_override or label,
                 fillcolor=fill_override or fill, **base)
        shown.add(node.name)

    for node in symbol._topo_nodes():
        if node.is_variable:
            continue
        for inp, _ in node.inputs:
            if inp.name not in shown:
                continue
            attrs = {"dir": "back", "arrowtail": "open"}
            out = edge_shape.get(inp.name)
            if out:
                attrs["label"] = "x".join(str(d) for d in out[1:])
            dot.edge(tail_name=node.name, head_name=inp.name, **attrs)
    return dot
