"""Attribute scoping (reference: python/mxnet/attribute.py AttrScope).

``with mx.AttrScope(ctx_group='dev1'):`` tags every symbol created inside
with the given attributes — the mechanism behind model-parallel layer
placement (reference: example/model-parallel-lstm/lstm.py:48-112). In the
TPU build ctx_group strings map onto mesh axes / devices via the parallel
layer (mxnet_tpu/parallel/).
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    _local = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be strings")
        self._attr = kwargs
        self._old = None

    @classmethod
    def _current(cls):
        return getattr(cls._local, "scope", None)

    def get(self, attr):
        """Merge scope attrs into user attrs (user wins)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        current = AttrScope._current()
        if current is not None and current._attr:
            merged = current._attr.copy()
            merged.update(self._attr)
            self._attr = merged
        self._old = current
        AttrScope._local.scope = self
        return self

    def __exit__(self, *args):
        AttrScope._local.scope = self._old


def current_attrs(attr=None):
    scope = AttrScope._current()
    if scope is None:
        return attr if attr else {}
    return scope.get(attr)
