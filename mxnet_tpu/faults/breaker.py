"""Circuit breaker: consecutive failures -> open -> half-open probe.

The serving degradation primitive (the Nygard/Hystrix shape): a
dependency failing *consecutively* is structurally broken, and hammering
it wastes queue capacity and blows deadlines for requests that were
admitted only to fail. The breaker trips OPEN after ``threshold``
consecutive failures; while open, work is rejected fast (with a
retry-after hint) instead of queued to die. After ``cooldown_s`` the
breaker lets exactly ONE probe through (HALF-OPEN); a probe success
closes the circuit, a probe failure re-opens it for another cooldown.

Time comes in through the caller (scheduler-clock seconds), never read
here, so the serving tests drive the full state machine on a FakeClock.
State transitions are observable: ``breaker.transitions{to=...}``
counters and a ``breaker.state`` gauge (0 closed / 1 half-open /
2 open), labeled with whatever identity the owner passes (the serving
registry labels per model).
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from .. import telemetry as _telemetry

__all__ = ["CircuitBreaker", "CircuitOpenError"]

_STATE_GAUGE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitOpenError(MXNetError):
    """Rejected fast: the target's circuit breaker is open.
    ``retry_after_ms`` hints when the next probe becomes possible."""

    def __init__(self, site, retry_after_s=0.0):
        self.site = site
        self.retry_after_ms = max(0, int(retry_after_s * 1000))
        super().__init__(
            f"{site}: circuit breaker open after consecutive failures; "
            f"retry after ~{self.retry_after_ms}ms")


class CircuitBreaker:
    """closed -> (threshold consecutive failures) -> open ->
    (cooldown) -> half-open probe -> closed | open."""

    def __init__(self, threshold=5, cooldown_s=1.0, site="",
                 labels=None, metric_prefix="breaker"):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.site = site
        self._labels = dict(labels or {})
        self._prefix = metric_prefix
        self._lock = threading.Lock()
        self.state = "closed"
        self.consecutive_failures = 0
        self.opened_at = None
        self._probing = False
        _telemetry.gauge(f"{self._prefix}.state", **self._labels).set(0)

    def _transition(self, state, now=None):
        self.state = state
        if state == "open":
            self.opened_at = now
            self._probing = False
        elif state == "closed":
            self.opened_at = None
            self.consecutive_failures = 0
            self._probing = False
        _telemetry.counter(f"{self._prefix}.transitions", to=state,
                           **self._labels).inc()
        _telemetry.gauge(f"{self._prefix}.state",
                         **self._labels).set(_STATE_GAUGE[state])
        _telemetry.flightrec.note(f"{self._prefix}.transition",
                                  site=self.site, to=state,
                                  failures=self.consecutive_failures,
                                  **self._labels)

    # ---------------------------------------------------------- decisions
    def can_dispatch(self, now):
        """Pure read (for scheduling decisions): may work be attempted
        at ``now``? True when closed, when an open cooldown has elapsed
        (a probe is available), or half-open with no probe in flight."""
        if self.state == "closed":
            return True
        if self.state == "open":
            return now - self.opened_at >= self.cooldown_s
        return not self._probing

    def admit_allowed(self, now):
        """May new work be *accepted* at ``now``? Rejects only while
        open with the cooldown still running — once a probe is possible
        the queue must be allowed to hold the probe's work."""
        if self.state != "open":
            return True
        return now - self.opened_at >= self.cooldown_s

    def retry_after(self, now):
        """Seconds until the next probe becomes possible (0 unless the
        circuit is open with cooldown remaining)."""
        if self.state != "open" or self.opened_at is None:
            return 0.0
        return max(0.0, self.cooldown_s - (now - self.opened_at))

    # ----------------------------------------------------------- mutation
    def acquire(self, now):
        """Claim permission to attempt work now. In the open state an
        elapsed cooldown converts the claim into the half-open probe;
        returns False when no attempt is allowed. Pair every True with
        ``record_success``/``record_failure`` (or ``release`` if the
        attempt never happened)."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if now - self.opened_at < self.cooldown_s:
                    return False
                self._transition("half_open", now)
            if self._probing:
                return False
            self._probing = True
            return True

    def release(self):
        """Abandon an acquired probe without an outcome (nothing to
        dispatch after all)."""
        with self._lock:
            self._probing = False

    def record_success(self, now=None):
        with self._lock:
            self.consecutive_failures = 0
            if self.state != "closed":
                self._transition("closed", now)

    def record_failure(self, now):
        with self._lock:
            self.consecutive_failures += 1
            if self.state == "half_open" or (
                    self.state == "closed" and
                    self.consecutive_failures >= self.threshold):
                self._transition("open", now)
