"""Deterministic fault-injection plane: named points, scripted triggers.

The chaos suite (tests/test_chaos.py) can produce exactly one failure
mode — a killed process — and only at @slow multi-process cost. Every
other failure seam the robustness story cares about (a full disk under
the checkpoint writer, a transient collective error, a corrupt record
in the decode pipeline, a dispatch failure in the serving engine) was
untestable deterministically. This module is the FakeClock of failures:
each seam declares a *named injection point*::

    from mxnet_tpu import faults
    faults.point("ckpt.write", seq=seq)

and an operator/test arms the plane with a scripted trigger per point::

    MXNET_FAULTS="ckpt.write:nth=2;io.decode:prob=0.1,seed=7"
    # or programmatically, scoped:
    with faults.scope("kvstore.collective:nth=1"):
        ...

Trigger grammar (per point, comma-separated ``key=value`` tokens after
the ``point:`` prefix; see docs/faults.md for the catalog):

==================  ====================================================
``once``            fire on the first call only (= ``nth=1``)
``always``          fire on every call
``nth=N``           fire on exactly the Nth call (1-based)
``every=N``         fire on every Nth call
``first=K``         fire on the first K calls
``prob=P``          fire with probability P per call, from a private
                    ``random.Random(seed)`` stream (``seed=S``,
                    default 0) — deterministic across runs
``latency=D``       inject a delay instead of an error (``50ms``,
                    ``0.5s``, or bare seconds)
``error=KIND``      exception class to raise: ``fault`` (default,
                    :class:`InjectedFault`), ``os``, ``runtime``,
                    ``conn``, ``timeout``, ``value``
``msg=TEXT``        override the exception message
==================  ====================================================

Design constraints, mirroring telemetry's:

* **Compiled out when unarmed.** ``point()`` with no plane armed is one
  module-global load, one ``is None`` branch and a return — gated <1%
  on the K=8 fused-step hot path by benchmarks/fault_overhead.py (the
  same discipline benchmarks/telemetry_overhead.py enforces).
* **Deterministic.** Every trigger is a pure function of its private
  call counter (and, for ``prob``, a seeded private rng) — the same
  armed spec produces the same fault sequence on every run, which is
  what lets tier-1 assert exact degradation paths.
* **Observable.** Every fired injection bumps the
  ``faults.injected{point=...}`` counter and leaves a
  ``fault.injected`` flight-ring record, so crash reports and
  tools/diagnose.py show what the plane did to the run.
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
import time

from ..base import MXNetError
from .. import telemetry as _telemetry

__all__ = ["InjectedFault", "point", "configure", "scope", "clear",
           "enabled", "fired", "calls", "parse_spec", "KNOWN_POINTS"]


class InjectedFault(MXNetError):
    """The default exception an armed injection point raises. Carries
    ``mx_fault_point`` (every injected exception does, whatever its
    class) so handlers and tests can tell injected failures from real
    ones."""


# the seams instrumented in-tree (docs/faults.md catalog); arming an
# unknown point is allowed — user code can declare its own points
KNOWN_POINTS = (
    "ckpt.write",          # checkpoint commit (serialize+fsync+rename)
    "ckpt.d2h",            # snapshot device->host transfer
    "kvstore.collective",  # bucket all-reduce dispatch
    "io.decode",           # prefetch/decode of one batch
    "serve.dispatch",      # serving batch dispatch
    "serve.admit",         # serving admission
    "train.health.triage", # health-plane escalation ladder entry
)

_ERROR_KINDS = {
    "fault": InjectedFault,
    "os": OSError,
    "runtime": RuntimeError,
    "conn": ConnectionError,
    "timeout": TimeoutError,
    "value": ValueError,
}


def _parse_duration(tok):
    """'50ms' / '0.5s' / '0.05' -> seconds."""
    tok = tok.strip().lower()
    try:
        if tok.endswith("ms"):
            return float(tok[:-2]) / 1000.0
        if tok.endswith("s"):
            return float(tok[:-1])
        return float(tok)
    except ValueError:
        raise MXNetError(f"bad duration {tok!r} (want e.g. 50ms, 0.5s)")


class _Trigger:
    """One point's scripted trigger: mode + private counter/rng."""

    __slots__ = ("point", "mode", "n", "prob", "latency_s", "exc_cls",
                 "msg", "calls", "fired", "_rng")

    def __init__(self, point, spec):
        self.point = point
        self.mode = None          # "nth" | "every" | "first" | "prob"
        self.n = 0
        self.prob = None
        self.latency_s = None     # delay action instead of raise
        self.exc_cls = InjectedFault
        self.msg = None
        self.calls = 0
        self.fired = 0
        seed = 0
        for tok in spec.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok == "once":
                self.mode, self.n = "nth", 1
                continue
            if tok == "always":
                self.mode, self.n = "first", float("inf")
                continue
            if "=" not in tok:
                raise MXNetError(
                    f"MXNET_FAULTS: bad token {tok!r} for point "
                    f"{point!r} (want key=value, 'once' or 'always')")
            key, _, val = tok.partition("=")
            key = key.strip()
            if key in ("nth", "every", "first"):
                self.mode, self.n = key, int(val)
                if self.n < 1:
                    raise MXNetError(f"MXNET_FAULTS: {key}={val} must "
                                     "be >= 1")
            elif key == "prob":
                self.mode, self.prob = "prob", float(val)
                if not 0.0 <= self.prob <= 1.0:
                    raise MXNetError(f"MXNET_FAULTS: prob={val} outside "
                                     "[0, 1]")
            elif key == "seed":
                seed = int(val)
            elif key == "latency":
                self.latency_s = _parse_duration(val)
            elif key == "error":
                if val not in _ERROR_KINDS:
                    raise MXNetError(
                        f"MXNET_FAULTS: unknown error kind {val!r} "
                        f"(have: {sorted(_ERROR_KINDS)})")
                self.exc_cls = _ERROR_KINDS[val]
            elif key == "msg":
                self.msg = val
            else:
                raise MXNetError(f"MXNET_FAULTS: unknown key {key!r} "
                                 f"for point {point!r}")
        if self.mode is None and self.latency_s is None:
            raise MXNetError(
                f"MXNET_FAULTS: point {point!r} needs a trigger "
                "(once/always/nth=/every=/first=/prob=)")
        if self.mode is None:
            self.mode, self.n = "first", float("inf")  # bare latency=
        self._rng = random.Random(seed)

    def should_fire(self):
        """Advance the private counter; decide deterministically."""
        self.calls += 1
        if self.mode == "nth":
            return self.calls == self.n
        if self.mode == "every":
            return self.calls % self.n == 0
        if self.mode == "first":
            return self.calls <= self.n
        return self._rng.random() < self.prob


class _Plane:
    """One armed configuration: point name -> trigger."""

    def __init__(self, triggers):
        self.triggers = triggers
        self._lock = threading.Lock()

    def hit(self, name, ctx):
        trig = self.triggers.get(name)
        if trig is None:
            return
        with self._lock:
            fire = trig.should_fire()
            if fire:
                trig.fired += 1
                call = trig.calls
        if not fire:
            return
        _telemetry.counter("faults.injected", point=name).inc()
        _telemetry.flightrec.note(
            "fault.injected", point=name, call=call,
            action="delay" if trig.latency_s is not None else
            trig.exc_cls.__name__, **ctx)
        if trig.latency_s is not None:
            time.sleep(trig.latency_s)
            return
        exc = trig.exc_cls(trig.msg or
                           f"injected fault at point {name!r} "
                           f"(call {call})")
        exc.mx_fault_point = name
        raise exc


_active = None     # None = disarmed: the point() fast path


def parse_spec(spec):
    """``MXNET_FAULTS`` string (or dict point->trigger) -> trigger map."""
    if isinstance(spec, dict):
        return {p: _Trigger(p, s) for p, s in spec.items()}
    triggers = {}
    for clause in str(spec).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        pt, sep, trig = clause.partition(":")
        if not sep or not pt.strip():
            raise MXNetError(
                f"MXNET_FAULTS: bad clause {clause!r} "
                "(want point:trigger[,key=value...])")
        pt = pt.strip()
        if pt in triggers:
            raise MXNetError(f"MXNET_FAULTS: point {pt!r} configured "
                             "twice")
        triggers[pt] = _Trigger(pt, trig)
    return triggers


def point(name, **ctx):
    """One named injection site. A no-op (one global load + branch)
    unless the plane is armed AND has a trigger for ``name``; when the
    trigger decides to fire, raises the configured exception (marked
    with ``mx_fault_point``) or sleeps the configured latency. ``ctx``
    rides into the flight-ring record."""
    plane = _active
    if plane is not None:
        plane.hit(name, ctx)


def configure(spec):
    """Arm the plane from a spec string/dict; ``None``/empty disarms.
    Returns the previous configuration handle (for scope())."""
    global _active
    prev = _active
    _active = _Plane(parse_spec(spec)) if spec else None
    return prev


def clear():
    """Disarm the plane."""
    global _active
    _active = None


def enabled():
    return _active is not None


@contextlib.contextmanager
def scope(spec):
    """Arm ``spec`` for the duration of a with-block, restoring the
    previous arming after — the tier-1 testing idiom."""
    global _active
    prev = configure(spec)
    try:
        yield _active
    finally:
        _active = prev


def fired(name=None):
    """Injections fired so far: count for one point, or dict for all."""
    plane = _active
    trigs = plane.triggers if plane is not None else {}
    if name is not None:
        t = trigs.get(name)
        return t.fired if t is not None else 0
    return {p: t.fired for p, t in trigs.items()}


def calls(name=None):
    """Point traversals seen by armed triggers (fired or not) — the
    per-batch site count benchmarks/fault_overhead.py multiplies by the
    disabled per-call cost."""
    plane = _active
    trigs = plane.triggers if plane is not None else {}
    if name is not None:
        t = trigs.get(name)
        return t.calls if t is not None else 0
    return {p: t.calls for p, t in trigs.items()}


# arm from the environment once at import: the process-wide spec a
# production run or a chaos harness sets before launch
_env_spec = os.environ.get("MXNET_FAULTS", "")
if _env_spec:
    configure(_env_spec)
