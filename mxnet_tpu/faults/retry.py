"""Shared retry policy: exponential backoff + jitter + deadline budget.

One retry implementation for every seam the fault plane hardens — the
checkpoint writer (``ckpt.write``), the kvstore collective dispatch
(``kvstore.collective``) — instead of N ad-hoc loops with N different
bugs. A :class:`RetryPolicy` is data (attempt cap, backoff curve,
per-sleep cap, deadline budget), ``retry_call`` is the one loop, and
both are observable: ``retry.attempts`` / ``retry.retries`` /
``retry.giveups`` counters labeled by ``site``, plus a ``retry.attempt``
flight-ring record per retry, so diagnose/crash reports show exactly how
a degraded run limped along.

Policies default from ``MXNET_RETRY_<SITE>`` env vars
(``attempts=3,base=0.05,mult=2,max=2,deadline=30,jitter=0.1``; see
docs/env_var.md) so operators can tune a production seam without code.

The ``give_up`` hook is the policy escape hatch: it inspects each
failure and may return a *different* exception to raise immediately —
the kvstore uses it to convert a collective failure into
``DeadWorkerError`` when the liveness layer says a peer actually died
(retrying a collective against a dead peer would burn the whole backoff
budget for nothing).
"""
from __future__ import annotations

import os
import random
import time

from ..base import MXNetError
from .. import telemetry as _telemetry

__all__ = ["RetryPolicy", "retry_call"]


def _parse_kv(raw, site):
    out = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise MXNetError(f"MXNET_RETRY_{site}: bad token {tok!r} "
                             "(want key=value)")
        k, _, v = tok.partition("=")
        out[k.strip()] = v.strip()
    return out


class RetryPolicy:
    """Data for one seam's retry behavior.

    attempts : total tries including the first (1 = no retry).
    base_s / multiplier / max_s : exponential backoff curve —
        sleep ``min(max_s, base_s * multiplier**(k-1))`` after the k-th
        failure.
    jitter : +-fraction of each sleep drawn from a private seeded rng
        (decorrelates a fleet retrying in lockstep; seed it for
        deterministic tests).
    deadline_s : total wall-budget across all attempts; when the next
        backoff would overrun it, give up instead.
    retry_on : exception classes worth retrying (everything else
        propagates immediately).
    sleep : injectable sleep (a FakeClock's in tests).
    """

    __slots__ = ("attempts", "base_s", "multiplier", "max_s", "jitter",
                 "deadline_s", "retry_on", "sleep", "_rng")

    def __init__(self, attempts=3, base_s=0.05, multiplier=2.0, max_s=2.0,
                 jitter=0.1, deadline_s=None, retry_on=(Exception,),
                 sleep=time.sleep, seed=None):
        self.attempts = max(1, int(attempts))
        self.base_s = float(base_s)
        self.multiplier = float(multiplier)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.retry_on = tuple(retry_on)
        self.sleep = sleep
        self._rng = random.Random(seed)

    def backoff(self, failure_count):
        """Sleep seconds after the ``failure_count``-th failure
        (1-based)."""
        d = min(self.max_s,
                self.base_s * self.multiplier ** (failure_count - 1))
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)

    @classmethod
    def from_env(cls, site, **defaults):
        """Policy for one seam, overridable via ``MXNET_RETRY_<SITE>``
        (e.g. ``MXNET_RETRY_CKPT="attempts=5,base=0.1,deadline=60"``).
        ``defaults`` supply the in-tree per-seam baseline."""
        raw = os.environ.get(f"MXNET_RETRY_{site.upper()}", "")
        kw = dict(defaults)
        if raw:
            keymap = {"attempts": ("attempts", int),
                      "base": ("base_s", float),
                      "mult": ("multiplier", float),
                      "max": ("max_s", float),
                      "deadline": ("deadline_s", float),
                      "jitter": ("jitter", float)}
            for k, v in _parse_kv(raw, site.upper()).items():
                if k not in keymap:
                    raise MXNetError(
                        f"MXNET_RETRY_{site.upper()}: unknown key {k!r} "
                        f"(have: {sorted(keymap)})")
                name, conv = keymap[k]
                try:
                    kw[name] = conv(v)
                except ValueError:
                    raise MXNetError(
                        f"MXNET_RETRY_{site.upper()}: bad value "
                        f"{k}={v!r}")
        return cls(**kw)


def retry_call(fn, policy=None, site="", give_up=None, logger=None):
    """Run ``fn()`` under ``policy``; return its result or raise.

    ``give_up(exc)`` (optional) inspects each retryable failure first:
    returning an exception raises it immediately (chained off the
    original), returning None lets the policy decide. Non-``retry_on``
    exceptions always propagate untouched.
    """
    policy = policy or RetryPolicy()
    start = time.monotonic()
    failures = 0
    while True:
        _telemetry.counter("retry.attempts", site=site).inc()
        try:
            return fn()
        except policy.retry_on as exc:
            failures += 1
            if give_up is not None:
                hard = give_up(exc)
                if hard is not None:
                    _telemetry.counter("retry.giveups", site=site).inc()
                    _telemetry.flightrec.note(
                        "retry.giveup", site=site, failures=failures,
                        converted=type(hard).__name__,
                        error=f"{type(exc).__name__}: {exc}")
                    raise hard from exc
            delay = policy.backoff(failures)
            out_of_budget = (
                policy.deadline_s is not None and
                time.monotonic() - start + delay > policy.deadline_s)
            if failures >= policy.attempts or out_of_budget:
                _telemetry.counter("retry.giveups", site=site).inc()
                _telemetry.flightrec.note(
                    "retry.giveup", site=site, failures=failures,
                    reason="deadline" if out_of_budget else "attempts",
                    error=f"{type(exc).__name__}: {exc}")
                raise
            _telemetry.counter("retry.retries", site=site).inc()
            _telemetry.flightrec.note(
                "retry.attempt", site=site, failures=failures,
                delay_ms=int(delay * 1000),
                error=f"{type(exc).__name__}: {exc}")
            if logger is not None:
                logger.warning(
                    "%s failed (attempt %d/%d): %s — retrying in %.3fs",
                    site or "call", failures, policy.attempts, exc, delay)
            if delay:
                policy.sleep(delay)
