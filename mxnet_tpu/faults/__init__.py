"""Deterministic fault-injection plane + shared degradation policies.

Three pieces, one robustness story (docs/faults.md):

* ``faults.plane`` — named injection points threaded through the
  framework's failure seams (checkpoint commit, snapshot D2H, kvstore
  collective, IO decode, serving dispatch/admission), armed by
  ``MXNET_FAULTS`` or ``faults.scope(...)`` with seeded/scripted
  triggers, compiled down to one branch when unarmed. This is what lets
  tier-1 prove every degradation path deterministically — the FakeClock
  of failures.
* ``faults.retry`` — the shared :class:`RetryPolicy` / ``retry_call``
  (exponential backoff + jitter + deadline budget, ``MXNET_RETRY_*``
  env, telemetry counters) applied at the seams where a transient
  failure should be survived: checkpoint writes, collective dispatch.
* ``faults.breaker`` — :class:`CircuitBreaker` (consecutive failures
  -> open -> half-open probe), the serving registry's per-model
  degradation primitive.

Pure stdlib + telemetry at import time, so every layer can import it
without ordering constraints (the same rule telemetry follows).
"""
from __future__ import annotations

from .plane import (InjectedFault, point, configure, scope, clear,
                    enabled, fired, calls, parse_spec, KNOWN_POINTS)
from .retry import RetryPolicy, retry_call
from .breaker import CircuitBreaker, CircuitOpenError
from . import plane
from . import retry
from . import breaker

__all__ = ["InjectedFault", "point", "configure", "scope", "clear",
           "enabled", "fired", "calls", "parse_spec", "KNOWN_POINTS",
           "RetryPolicy", "retry_call", "CircuitBreaker",
           "CircuitOpenError", "plane", "retry", "breaker"]
