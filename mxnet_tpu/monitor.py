"""Monitor: per-op tensor statistics for debugging training.

API parity with reference python/mxnet/monitor.py backed by this
framework's per-op tap: installing a monitor switches the executor's
forward pass to eager per-node dispatch so *every* operator output is
observed (the analog of graph_executor.cc:758-778 ExecuteMonCallback),
not just the graph outputs. Weights are sampled at ``toc`` time.

Usage (same as the reference)::

    mon = Monitor(interval=2, pattern=".*fc.*")
    mod.fit(..., monitor=mon)        # or mon.install(executor)
    # per interval: mon.tic() before forward, mon.toc_print() after
"""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray
from . import ndarray as nd
from . import telemetry as _telemetry

__all__ = ["Monitor"]

log = logging.getLogger(__name__)


def _abs_mean(x):
    """Default statistic: mean of |x| — cheap and NaN-revealing."""
    return nd.abs(x).asnumpy().mean()


class Monitor:
    """Collects ``stat_func`` over op outputs (and weights) every
    ``interval`` training steps, for tensor names matching ``pattern``."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _abs_mean
        self.sort = sort
        self._pattern = re.compile(pattern)
        self._executors = []
        self._records = []       # (step, tensor_name, stat)
        self._step = 0
        self._window_step = 0    # the step the open window belongs to
        self._recording = False

    # the executor calls this for every op output while recording
    def _observe(self, name, array):
        if self._recording and self._pattern.match(name):
            self._records.append(
                (self._window_step, name, self.stat_func(array)))

    def install(self, exe):
        """Attach to an Executor (Module installs on its sharded exec)."""
        exe.set_monitor_callback(self._observe)
        self._executors.append(exe)

    # reference spelling
    install_exe = install

    def tic(self):
        """Start a recording window if this step is on the interval."""
        if self._step % self.interval == 0:
            self._records = []
            self._recording = True
            self._window_step = self._step
        self._step += 1

    def toc(self):
        """Close the window; returns [(step, name, stat_str)] collected.

        Every tuple — op outputs observed during the window AND the
        weights sampled here — carries the same step: the step the
        window was opened on (``tic`` time), so records key consistently
        as (step, name) across a whole training run."""
        if not self._recording:
            return []
        # sample bound weights too, like the reference toc does
        for exe in self._executors:
            for name, arr in zip(exe.arg_names, exe.arg_arrays):
                if arr is not None and self._pattern.match(name):
                    self._records.append(
                        (self._window_step, name, self.stat_func(arr)))
        self._recording = False
        out = sorted(self._records, key=lambda r: (r[1], r[0])) \
            if self.sort else list(self._records)
        self._records = []
        enabled = _telemetry.enabled()
        for step, name, val in out:
            try:
                fval = float(val)
            except (TypeError, ValueError):
                continue
            if enabled:
                _telemetry.gauge("monitor.stat", tensor=name).set(fval)
                _telemetry.record_event("monitor", step=step, name=name,
                                        value=fval)
            if fval != fval or fval in (float("inf"), float("-inf")):
                # a non-finite statistic is the classic divergence tell —
                # put it in the always-on flight ring so a later crash
                # report carries the first sighting even without the
                # tracer or a sentinel installed
                _telemetry.flightrec.note("anomaly", what="monitor_stat",
                                          array=name, step=step)
        return [(step, name, str(val)) for step, name, val in out]

    def flush(self):
        """Drop any queued stats and close the window, so interrupted
        tic/toc cycles (an exception mid-batch, interval changes, or a
        toc that never came) can't leak entries into the next window."""
        self._records = []
        self._recording = False

    def toc_print(self):
        """toc() + log each record."""
        for step, name, val in self.toc():
            log.info("monitor step %d  %-30s %s", step, name, val)
