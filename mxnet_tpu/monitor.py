"""Monitor: per-op tensor statistics for debugging training.

API parity with reference python/mxnet/monitor.py backed by this
framework's per-op tap: installing a monitor switches the executor's
forward pass to eager per-node dispatch so *every* operator output is
observed (the analog of graph_executor.cc:758-778 ExecuteMonCallback),
not just the graph outputs. Weights are sampled at ``toc`` time.

Usage (same as the reference)::

    mon = Monitor(interval=2, pattern=".*fc.*")
    mod.fit(..., monitor=mon)        # or mon.install(executor)
    # per interval: mon.tic() before forward, mon.toc_print() after
"""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["Monitor"]

log = logging.getLogger(__name__)


def _abs_mean(x):
    """Default statistic: mean of |x| — cheap and NaN-revealing."""
    return nd.abs(x).asnumpy().mean()


class Monitor:
    """Collects ``stat_func`` over op outputs (and weights) every
    ``interval`` training steps, for tensor names matching ``pattern``."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _abs_mean
        self.sort = sort
        self._pattern = re.compile(pattern)
        self._executors = []
        self._records = []       # (step, tensor_name, stat)
        self._step = 0
        self._recording = False

    # the executor calls this for every op output while recording
    def _observe(self, name, array):
        if self._recording and self._pattern.match(name):
            self._records.append((self._step, name, self.stat_func(array)))

    def install(self, exe):
        """Attach to an Executor (Module installs on its sharded exec)."""
        exe.set_monitor_callback(self._observe)
        self._executors.append(exe)

    # reference spelling
    install_exe = install

    def tic(self):
        """Start a recording window if this step is on the interval."""
        if self._step % self.interval == 0:
            self._records = []
            self._recording = True
        self._step += 1

    def toc(self):
        """Close the window; returns [(step, name, stat_str)] collected."""
        if not self._recording:
            return []
        self._recording = True
        # sample bound weights too, like the reference toc does
        for exe in self._executors:
            for name, arr in zip(exe.arg_names, exe.arg_arrays):
                if arr is not None and self._pattern.match(name):
                    self._records.append(
                        (self._step, name, self.stat_func(arr)))
        self._recording = False
        out = sorted(self._records, key=lambda r: r[1]) if self.sort \
            else list(self._records)
        self._records = []
        return [(step, name, str(val)) for step, name, val in out]

    def toc_print(self):
        """toc() + log each record."""
        for step, name, val in self.toc():
            log.info("monitor step %d  %-30s %s", step, name, val)
