"""RecordIO container format (reference: python/mxnet/recordio.py, 269 LoC;
framing from dmlc-core recordio.h).

Byte-compatible with the reference's RecordIO: records framed as
``[kMagic:4][lrec:4][data][pad to 4]`` where lrec packs cflag (3 bits) and
length (29 bits). ``IRHeader`` packing matches mx.recordio.pack so existing
``.rec`` datasets and ``im2rec`` output load unchanged.
"""
from __future__ import annotations

import os
import struct
import numbers
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_K_MAGIC = 0xced7230a


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential RecordIO reader/writer. reference: recordio.py:15-90."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        self.handle.close()
        self.is_open = False

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        data = bytes(buf)
        # single-record framing (no multi-part splitting needed host-side)
        self.handle.write(struct.pack("<II", _K_MAGIC,
                                      _encode_lrec(0, len(data))))
        self.handle.write(data)
        pad = (4 - len(data) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        header = self.handle.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _K_MAGIC:
            raise MXNetError("invalid RecordIO magic")
        _, length = _decode_lrec(lrec)
        data = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return data

    def tell(self):
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a .idx sidecar for random access.
    reference: recordio.py:92-160."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + raw bytes. reference: recordio.py:180 (IRHeader)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack to (IRHeader, payload). reference: recordio.py:200."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack header + encoded image. Requires cv2 or PIL (gated)."""
    encoded = _encode_img(img, quality, img_fmt)
    return pack(header, encoded)


def unpack_img(s, iscolor=-1):
    """Unpack to (IRHeader, decoded image ndarray)."""
    header, s = unpack(s)
    img = _decode_img(s, iscolor)
    return header, img


def _encode_img(img, quality, img_fmt):
    try:
        import cv2
        ret, buf = cv2.imencode(
            img_fmt, img, [cv2.IMWRITE_JPEG_QUALITY, quality]
            if img_fmt in (".jpg", ".jpeg") else [])
        assert ret
        return buf.tobytes()
    except ImportError:
        pass
    try:
        from PIL import Image
        import io as _io
        bio = _io.BytesIO()
        Image.fromarray(img[..., ::-1] if img.ndim == 3 else img).save(
            bio, format="JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG",
            quality=quality)
        return bio.getvalue()
    except ImportError:
        raise MXNetError("pack_img requires cv2 or PIL")


def _decode_img(s, iscolor):
    try:
        import cv2
        return cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    except ImportError:
        pass
    try:
        from PIL import Image
        import io as _io
        img = np.asarray(Image.open(_io.BytesIO(s)))
        if img.ndim == 3:
            img = img[..., ::-1]  # RGB -> BGR to match cv2 convention
        return img
    except ImportError:
        raise MXNetError("unpack_img requires cv2 or PIL")
