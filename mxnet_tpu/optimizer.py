"""Optimizers (reference: python/mxnet/optimizer.py, 755 LoC).

Same registry/API contract as the reference: ``Optimizer.create_optimizer``,
``create_state``/``update`` per weight index, ``lr_mult``/``wd_mult`` pulled
from symbol attrs (``__lr_mult__``), ``rescale_grad``, ``clip_gradient``,
``get_updater`` closure for the KVStore local-update path.

The hot updates (SGD/momentum/Adam/RMSProp) call the fused update ops
(mxnet_tpu/ops/optimizer_op.py) exactly as the reference calls
``mx.nd.sgd_update`` etc. (reference: optimizer.py:278-320) — one XLA kernel
per weight, buffers donated/swapped in place. The rest are expressed in
NDArray arithmetic (still fused by XLA at trace time under jit).
"""
from __future__ import annotations

import math
import logging

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, zeros, imperative_invoke
from . import ndarray as nd

__all__ = ["Optimizer", "SGD", "DCASGD", "NAG", "SGLD", "ccSGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Test", "create",
           "get_updater", "Updater", "register"]


class Optimizer:
    """Base optimizer. reference: optimizer.py:21-277."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f"Cannot find optimizer {name}")

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict)
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    #: True when ``fused_plan``'s update is elementwise over
    #: (weight, grad, state) — each output element depends only on the
    #: same-index input elements. That makes the update exact on any
    #: flat reshape/shard of the buffers, which is what the ZeRO-1
    #: sharded-update plan (parallel/zero.py) requires; non-elementwise
    #: optimizers keep the replicated update.
    fused_update_elementwise = False

    def fused_plan(self):
        """Optional fused-train-step support.

        Returns ``(init_state, update)`` of pure jax functions —
        ``init_state(weight_array) -> state_pytree`` and
        ``update(weight, grad, state, lr, wd) -> (new_weight, new_state)``
        with lr/wd as traced scalars — or None when this optimizer can
        only run imperatively. Used by Module's fused train step, which
        compiles forward+backward+update into ONE XLA program (the
        TPU-native analog of the reference's bulk-exec + fused update
        ops; the imperative ``update()`` path remains for kvstore and
        custom flows).
        """
        return None

    def fused_plan_token(self):
        """Hashable token identifying the traced structure AND baked-in
        constants of ``fused_plan``'s closures — the program-cache key
        component for fused/scan train programs (program_cache.py).
        Subclasses with a fused_plan must extend this with every
        hyperparameter their update closure captures by value."""
        return (type(self).__name__, float(self.rescale_grad),
                float(self.clip_gradient) if self.clip_gradient else -1.0)

    def _fused_grad_prep(self):
        """Shared grad preprocessing closure for fused_plan impls."""
        import jax.numpy as jnp
        rescale = self.rescale_grad
        clip = self.clip_gradient if self.clip_gradient else -1.0

        def prep(g, w, wd):
            g = g * rescale
            if clip > 0:
                g = jnp.clip(g, -clip, clip)
            return g + wd * w
        return prep

    def set_lr_mult(self, args_lr_mult):
        """reference: optimizer.py set_lr_mult — reads __lr_mult__ attrs."""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """bias/gamma/beta default to wd_mult=0. reference: optimizer.py."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd


register = Optimizer.register
create = Optimizer.create_optimizer


def _state_like(weight):
    """Zeroed state with the SAME device placement/sharding as the weight.

    Critical for mesh-sharded training: a replicated weight needs replicated
    optimizer state or the fused update op sees incompatible devices.
    """
    import jax
    import jax.numpy as jnp
    arr = weight.asjax()
    return NDArray(jax.device_put(jnp.zeros(arr.shape, arr.dtype),
                                  arr.sharding))


def _clip(arr, bound):
    if bound is None or bound <= 0:
        return arr
    return nd.clip(arr, a_min=-bound, a_max=bound)


@register
class SGD(Optimizer):
    """SGD with momentum via the fused ops. reference: optimizer.py:279."""

    fused_update_elementwise = True     # w/g/mom math is per-element

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _state_like(weight)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=self.clip_gradient
                      if self.clip_gradient else -1.0)
        if state is not None:
            imperative_invoke("sgd_mom_update", weight, grad, state,
                              momentum=self.momentum, **kwargs)
        else:
            imperative_invoke("sgd_update", weight, grad, **kwargs)

    def fused_plan(self):
        import jax.numpy as jnp
        prep = self._fused_grad_prep()
        momentum = self.momentum

        def init_state(w):
            return jnp.zeros_like(w) if momentum else ()

        def update(w, g, s, lr, wd):
            g = prep(g, w, wd)
            if momentum:
                new_s = momentum * s - lr * g
                return w + new_s, new_s
            return w - lr * g, ()
        return init_state, update

    def fused_plan_token(self):
        return super().fused_plan_token() + (float(self.momentum),)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD. reference: optimizer.py:325."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_state_like(weight),
                weight.copy())

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (grad + wd * weight + self.lamda * grad * grad *
                       (weight - previous_weight))
        if mom is not None:
            mom *= self.momentum
            mom += delta
            delta = mom
        previous_weight._set(weight.asjax())
        weight += delta


@register
class NAG(SGD):
    """Nesterov accelerated SGD. reference: optimizer.py:380."""

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad = grad + wd * weight
            mom += grad
            grad = grad + self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)

    def fused_plan(self):
        # own plan: inheriting SGD's would fuse plain-momentum math
        # while the staged path runs Nesterov (same update() as above)
        import jax.numpy as jnp
        prep = self._fused_grad_prep()
        momentum = self.momentum

        def init_state(w):
            return jnp.zeros_like(w) if momentum else ()

        def update(w, g, s, lr, wd):
            g = prep(g, w, wd)
            if momentum:
                new_s = momentum * s + g
                return w - lr * (g + momentum * new_s), new_s
            return w - lr * g, ()
        return init_state, update


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics. reference: optimizer.py:416."""

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        noise = nd.random_normal(shape=weight.shape,
                                 scale=math.sqrt(lr),
                                 dtype=str(weight.dtype))
        weight += -lr / 2 * (grad + wd * weight) + noise


@register
class ccSGD(SGD):
    """Compat alias of SGD (the reference's C++-side SGD).
    reference: optimizer.py:445."""


@register
class Adam(Optimizer):
    """reference: optimizer.py:451 (Kingma & Ba, with bias correction)."""

    fused_update_elementwise = True     # w/g/mean/var math is per-element

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_state_like(weight),
                _state_like(weight))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        imperative_invoke("adam_update", weight, grad, mean, var,
                          lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                          epsilon=self.epsilon,
                          rescale_grad=self.rescale_grad,
                          clip_gradient=self.clip_gradient
                          if self.clip_gradient else -1.0)

    def fused_plan(self):
        # bias correction rides on lr, which Module computes per step via
        # _get_lr + the update count (same as the imperative path above)
        import jax.numpy as jnp
        prep = self._fused_grad_prep()
        b1, b2, eps = self.beta1, self.beta2, self.epsilon

        def init_state(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, s, lr, wd):
            mean, var = s
            g = prep(g, w, wd)
            new_mean = b1 * mean + (1 - b1) * g
            new_var = b2 * var + (1 - b2) * jnp.square(g)
            new_w = w - lr * new_mean / (jnp.sqrt(new_var) + eps)
            return new_w, (new_mean, new_var)
        return init_state, update

    def fused_plan_token(self):
        return super().fused_plan_token() + (
            float(self.beta1), float(self.beta2), float(self.epsilon))

    def fused_lr_scale(self, t):
        """Per-step lr multiplier (bias correction) for the fused path."""
        return math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)


@register
class AdaGrad(Optimizer):
    """reference: optimizer.py:499."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _state_like(weight)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / nd.sqrt(history + self.float_stable_eps) +
                         wd * weight)


@register
class RMSProp(Optimizer):
    """reference: optimizer.py:536 (Tieleman or Graves variant)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_state_like(weight),
                    _state_like(weight),
                    _state_like(weight))
        return (_state_like(weight),)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      gamma1=self.gamma1, epsilon=self.epsilon,
                      clip_gradient=self.clip_gradient
                      if self.clip_gradient else -1.0,
                      clip_weights=self.clip_weights
                      if self.clip_weights else -1.0)
        if not self.centered:
            (n,) = state
            imperative_invoke("rmsprop_update", weight, grad, n, **kwargs)
        else:
            n, g, delta = state
            imperative_invoke("rmspropalex_update", weight, grad, n, g,
                              delta, gamma2=self.gamma2, **kwargs)


@register
class AdaDelta(Optimizer):
    """reference: optimizer.py:605 (Zeiler 2012)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_state_like(weight),
                _state_like(weight))

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set((self.rho * acc_g + (1.0 - self.rho) * grad * grad)
                   .asjax())
        current_delta = (nd.sqrt(acc_delta + self.epsilon) /
                         nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta._set((self.rho * acc_delta + (1.0 - self.rho) *
                        current_delta * current_delta).asjax())
        weight._set((weight - current_delta - wd * weight).asjax())


@register
class Ftrl(Optimizer):
    """reference: optimizer.py:654 (McMahan et al.)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_state_like(weight),
                _state_like(weight))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        z, n = state
        sigma = -nd.sqrt(n)
        n += grad * grad
        denom = nd.sqrt(n)
        sigma += denom
        sigma /= lr
        z += grad - sigma * weight
        # update weight
        d = (self.beta + denom) / lr + wd
        sign_z = nd.sign(z)
        new_w = (sign_z * self.lamda1 - z) / d * \
            (nd.abs(z) > self.lamda1)
        weight._set(new_w.asjax())


@register
class Test(Optimizer):
    """Mock optimizer for kvstore tests. reference: optimizer.py:706."""

    def create_state(self, index, weight):
        return _state_like(weight)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._set(weight.asjax())


class Updater:
    """KVStore updater (reference: optimizer.py:722-740) — states
    created lazily per key; picklable state transport for the dist
    server protocol's set/get."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index,
                                                             weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        import pickle
        self.states = pickle.loads(states)

    def get_states(self):
        import pickle
        return pickle.dumps(self.states)


def get_updater(optimizer):
    """reference: optimizer.py get_updater -> Updater instance."""
    return Updater(optimizer)
