"""NDArray: the imperative tensor of the framework.

The reference NDArray (reference: include/mxnet/ndarray.h:58-400,
src/ndarray/ndarray.cc) is a ref-counted handle over device storage whose
every mutation is pushed to the dependency engine with the handle's ``var()``
as a write dependency; ``WaitToRead``/``asnumpy`` are the sync points.

TPU-native design: an NDArray is a *mutable cell holding an immutable
jax.Array*. JAX's async dispatch IS the dependency engine — ops return
futures immediately and XLA orders them by data dependence, so there is no
Var/Opr machinery to rebuild (SURVEY.md §7 design mapping). Mutation
(``+=``, slice assignment, optimizer updates) is realized by computing a new
immutable array and swapping it into the cell, which keeps every Python alias
coherent — the exact property the executor's arg_dict aliasing relies on
(reference: python/mxnet/module/executor_group.py:233-268).

Sync points: ``asnumpy()``/``wait_to_read()`` -> ``block_until_ready`` —
matching MXNet's "async everywhere, sync on read" contract.
"""
from __future__ import annotations

import struct

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, current_context
from .ops.registry import OP_REGISTRY, get_op
from . import random as _random
from .telemetry import memory as _memory

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "load", "save", "waitall", "imperative_invoke",
           "add", "subtract", "multiply", "divide", "true_divide",
           "power", "maximum", "minimum", "equal", "not_equal", "greater",
           "greater_equal", "lesser", "lesser_equal", "moveaxis",
           "onehot_encode", "imdecode"]

# Registry op functions (slice, abs, sum, ...) are injected into this module
# at package init (_op_gen), shadowing python builtins of the same name —
# capture the builtins first.
_py_slice, _py_abs, _py_sum, _py_max, _py_min = slice, abs, sum, max, min


class NDArray:
    """Mutable handle over an immutable jax.Array."""

    __slots__ = ("_data", "_ctx", "writable", "_acct")

    def __init__(self, data, ctx=None, writable=True):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            if ctx is not None:
                # host data goes straight to the target device — going
                # through jnp.asarray first would land it on the DEFAULT
                # device and turn this into a cross-device round-trip
                # (catastrophic when the default device is a remote chip)
                data = jax.device_put(np.asarray(data), ctx.jax_device())
            else:
                data = jnp.asarray(data)
        elif ctx is not None and not _placement_matches(data, ctx):
            # move only across platforms; within a platform keep the
            # array's existing (possibly mesh-sharded) placement — a
            # Context names the logical home, not a single shard
            data = jax.device_put(data, ctx.jax_device())
        self._data = data
        self._ctx = ctx if ctx is not None else _infer_ctx(data)
        self.writable = writable
        _memory.on_alloc(self)   # per-context live/peak byte accounting

    # ------------------------------------------------------------------ core
    def asjax(self):
        """The underlying immutable jax.Array."""
        return self._data

    def _set(self, new_data):
        """Swap in a new buffer (the mutation primitive)."""
        if not self.writable:
            raise MXNetError("trying to write to a read-only NDArray")
        self._data = new_data
        _memory.on_swap(self)    # re-account only when the size changed

    def __del__(self):
        try:
            _memory.on_free(self._acct)
        except Exception:
            pass                 # interpreter shutdown / half-built handle

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self):
        """Copy to host numpy — THE sync point (block_until_ready)."""
        return np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def wait_to_read(self):
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    def copyto(self, other):
        """Copy into another NDArray or Context.

        reference: ndarray.cc CopyFromTo 4-way device dispatch; here
        jax.device_put covers every direction (host<->TPU, TPU<->TPU).
        """
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise ValueError(
                    f"copyto shape mismatch {self.shape} vs {other.shape}")
            # land in the destination's existing placement (preserves
            # mesh shardings; moves across platforms when needed)
            other._set(jax.device_put(
                self._data.astype(other.dtype), other._data.sharding))
            return other
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, ctx):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def copy(self):
        return NDArray(self._data, ctx=self._ctx)

    def astype(self, dtype):
        return NDArray(self._data.astype(np.dtype(dtype)), ctx=self._ctx)

    def reshape(self, shape, **kwargs):
        if isinstance(shape, int):
            shape = (shape,)
        if kwargs.get("reverse"):
            raise NotImplementedError("reshape(reverse=True)")
        shape = tuple(int(s) for s in shape)
        # -1 / 0 special values per reference Reshape semantics
        shape = _resolve_reshape(self.shape, shape)
        return NDArray(jnp.reshape(self._data, shape), ctx=self._ctx)

    @property
    def T(self):
        return NDArray(self._data.T, ctx=self._ctx)

    # --------------------------------------------------------------- getters
    def __getitem__(self, key):
        out = self._data[key]
        return NDArray(out, ctx=self._ctx)

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, _py_slice) and key == _py_slice(None):
            new = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype),
                                   self.shape).astype(self.dtype)
        else:
            new = self._data.at[key].set(
                value if not np.isscalar(value) else value)
        self._set(new.astype(self.dtype))

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self.asscalar())

    def __repr__(self):
        return (f"{self.asnumpy()!r}\n<NDArray {'x'.join(map(str, self.shape))}"
                f" @{self._ctx}>")

    # ----------------------------------------------------------- arithmetic
    def _binary(self, other, fn, rfn=None):
        if isinstance(other, NDArray):
            out = NDArray(fn(self._data, other._data), ctx=self._ctx)
            _maybe_tape(fn, [self, other], out)
            return out
        if isinstance(other, (int, float, np.generic)):
            out = NDArray(fn(self._data, other), ctx=self._ctx)
            _maybe_tape(lambda a, _o=other: fn(a, _o), [self], out)
            return out
        return NotImplemented

    def __add__(self, o): return self._binary(o, jnp.add)
    __radd__ = __add__
    def __sub__(self, o): return self._binary(o, jnp.subtract)
    def __rsub__(self, o): return self._binary(o, lambda a, b: jnp.subtract(b, a))
    def __mul__(self, o): return self._binary(o, jnp.multiply)
    __rmul__ = __mul__
    def __truediv__(self, o): return self._binary(o, jnp.divide)
    def __rtruediv__(self, o): return self._binary(o, lambda a, b: jnp.divide(b, a))
    __div__, __rdiv__ = __truediv__, __rtruediv__
    def __mod__(self, o): return self._binary(o, jnp.mod)
    def __pow__(self, o): return self._binary(o, jnp.power)
    def __rpow__(self, o): return self._binary(o, lambda a, b: jnp.power(b, a))
    def __neg__(self): return NDArray(-self._data, ctx=self._ctx)
    def __abs__(self): return NDArray(jnp.abs(self._data), ctx=self._ctx)

    def __iadd__(self, o):
        self._set((self + o)._data)
        return self

    def __isub__(self, o):
        self._set((self - o)._data)
        return self

    def __imul__(self, o):
        self._set((self * o)._data)
        return self

    def __itruediv__(self, o):
        self._set((self / o)._data)
        return self

    def __eq__(self, o): return self._binary(o, lambda a, b: (a == b).astype(a.dtype))
    def __ne__(self, o): return self._binary(o, lambda a, b: (a != b).astype(a.dtype))
    def __gt__(self, o): return self._binary(o, lambda a, b: (a > b).astype(a.dtype))
    def __ge__(self, o): return self._binary(o, lambda a, b: (a >= b).astype(a.dtype))
    def __lt__(self, o): return self._binary(o, lambda a, b: (a < b).astype(a.dtype))
    def __le__(self, o): return self._binary(o, lambda a, b: (a <= b).astype(a.dtype))
    __hash__ = object.__hash__


def _maybe_tape(fn, input_handles, out_handle):
    """Record an NDArray operator on the autograd tape while training."""
    from . import autograd as _ag
    if not _ag._STATE["train"]:
        return
    _ag._record_fn(lambda vals: [fn(*vals)], input_handles,
                   [h.asjax() for h in input_handles], [out_handle])


def _placement_matches(data, ctx):
    try:
        plat = next(iter(data.devices())).platform
    except Exception:
        return False
    want_cpu = ctx.device_type in ("cpu", "cpu_pinned")
    return (plat == "cpu") == want_cpu


def _infer_ctx(data):
    try:
        dev = list(data.devices())[0]
    except Exception:
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


def _resolve_reshape(old, new):
    out = []
    for i, s in enumerate(new):
        if s == 0:
            out.append(old[i])
        else:
            out.append(s)
    if -1 in out:
        known = int(np.prod([s for s in out if s != -1], dtype=np.int64))
        total = int(np.prod(old, dtype=np.int64))
        out[out.index(-1)] = total // _py_max(known, 1)
    return tuple(out)


# ---------------------------------------------------------------- factories
def _default_dtype(dtype):
    return np.dtype(dtype if dtype is not None else np.float32)


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like. reference: ndarray.py array()."""
    if isinstance(source_array, NDArray):
        src = source_array.asjax()
        if dtype is not None:
            src = src.astype(np.dtype(dtype))
        return NDArray(src, ctx=ctx or source_array.context)
    arr = np.asarray(source_array)
    if dtype is None:
        dtype = arr.dtype if arr.dtype != np.float64 else np.float32
    return NDArray(arr.astype(np.dtype(dtype), copy=False),
                   ctx=ctx or current_context())


def zeros(shape, ctx=None, dtype=None):
    return NDArray(jnp.zeros(shape, _default_dtype(dtype)),
                   ctx=ctx or current_context())


def ones(shape, ctx=None, dtype=None):
    return NDArray(jnp.ones(shape, _default_dtype(dtype)),
                   ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype=None):
    return NDArray(jnp.full(shape, val, _default_dtype(dtype)),
                   ctx=ctx or current_context())


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    arr = jnp.arange(start, stop, step, _default_dtype(dtype))
    if repeat > 1:
        arr = jnp.repeat(arr, repeat)
    return NDArray(arr, ctx=ctx or current_context())


def concatenate(arrays, axis=0, always_copy=True):
    if not arrays:
        raise ValueError("need at least one array")
    if len(arrays) == 1 and not always_copy:
        return arrays[0]
    return NDArray(jnp.concatenate([a.asjax() for a in arrays], axis=axis),
                   ctx=arrays[0].context)


# ------------------------------------------------------------- save / load
# Byte-compatible with the reference's .params container so checkpoints are
# interchangeable (reference: ndarray.cc:605-695 NDArray::Save/Load over
# dmlc::Stream; c_api.h:272-299). Layout, little-endian:
#   uint64 magic=0x112, uint64 reserved=0
#   uint64 narr; per array:
#     uint32 ndim, uint32[ndim] shape          (mshadow TShape::Save)
#     [if ndim>0] int32 dev_type, int32 dev_id (Context::Save)
#                 int32 type_flag, raw bytes   (mshadow type codes)
#   uint64 nkeys; per key: uint64 len, bytes
_MAGIC = 0x112
# mshadow type flags (mshadow/base.h): kFloat32..kInt64
_DTYPE_CODE = {np.dtype(d): i for i, d in enumerate(
    ["float32", "float64", "float16", "uint8", "int32", "int8", "int64"])}
# extension codes for the fp8 storage dtypes, parked far outside the
# reference range (0-6 here, <=12 in later mshadow revisions): a file
# carrying fp8 cells has no reference-framework reading anyway, while
# files restricted to the standard dtypes stay byte-for-byte compatible
try:
    _DTYPE_CODE[np.dtype("float8_e4m3fn")] = 100
    _DTYPE_CODE[np.dtype("float8_e5m2")] = 101
except TypeError:       # numpy without ml_dtypes registration
    pass
_CODE_DTYPE = {v: k for k, v in _DTYPE_CODE.items()}


def save(fname, data):
    """Save a list or str->NDArray dict. reference: mx.nd.save.

    The on-disk container matches the reference's dmlc::Stream format
    byte-for-byte for the standard dtypes, so ``prefix-XXXX.params``
    checkpoints round-trip between the two frameworks. bfloat16 arrays are
    widened to float32 on save (the 2017 format predates bf16).
    """
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    elif isinstance(data, NDArray):
        names, arrays = [], [data]
    else:
        raise TypeError("save requires dict/list/NDArray")
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQQ", _MAGIC, 0, len(arrays)))
        for arr in arrays:
            np_arr = arr.asnumpy() if isinstance(arr, NDArray) \
                else np.asarray(arr)
            dt = np.dtype(np_arr.dtype)
            if dt not in _DTYPE_CODE:
                np_arr = np_arr.astype(np.float32)
                dt = np.dtype(np.float32)
            f.write(struct.pack("<I", np_arr.ndim))
            f.write(struct.pack(f"<{np_arr.ndim}I", *np_arr.shape))
            f.write(struct.pack("<ii", 1, 0))  # Context: cpu(0)
            f.write(struct.pack("<i", _DTYPE_CODE[dt]))
            f.write(np.ascontiguousarray(np_arr).tobytes())
        f.write(struct.pack("<Q", len(names)))
        for name in names:
            b = name.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname):
    """Load NDArrays saved by :func:`save` or by the reference's mx.nd.save."""
    with open(fname, "rb") as f:
        magic, _reserved, n_arr = struct.unpack("<QQQ", f.read(24))
        if magic != _MAGIC:
            raise MXNetError(f"invalid NDArray file {fname}")
        arrays = []
        for _ in range(n_arr):
            ndim, = struct.unpack("<I", f.read(4))
            shape = struct.unpack(f"<{ndim}I", f.read(4 * ndim))
            if ndim == 0:  # is_none() array: shape only
                arrays.append(array(np.zeros((0,), np.float32)))
                continue
            struct.unpack("<ii", f.read(8))  # Context (ignored)
            dcode, = struct.unpack("<i", f.read(4))
            dt = _CODE_DTYPE[dcode]
            count = int(np.prod(shape, dtype=np.int64))
            buf = f.read(count * dt.itemsize)
            arrays.append(array(np.frombuffer(buf, dtype=dt).reshape(shape)))
        n_names, = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(n_names):
            ln, = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode())
    if names:
        return dict(zip(names, arrays))
    return arrays


def waitall():
    """Block until all async work is done. reference: MXNDArrayWaitAll."""
    (jax.device_put(0.0) + 0).block_until_ready()


# ------------------------------------------------------ imperative dispatch
def imperative_invoke(op_name, *inputs, out=None, **kwargs):
    """Run a registered op eagerly on NDArrays.

    The analog of MXImperativeInvoke (reference: c_api_ndarray.cc:322-420):
    resolve op -> normalize attrs -> run the JAX kernel (async) -> wrap/swap
    outputs. Ops that declare ``mutate_inputs`` (optimizer updates) have the
    new buffers swapped into the corresponding input handles.
    """
    opdef = get_op(op_name)
    attrs = opdef.normalize_attrs(kwargs)
    in_names = opdef.input_names(attrs)
    aux_n = len(opdef.aux_names(attrs))
    arrs = [x.asjax() if isinstance(x, NDArray) else jnp.asarray(x)
            for x in inputs]
    regular, aux = (arrs[:len(arrs) - aux_n], arrs[len(arrs) - aux_n:]) \
        if aux_n else (arrs, [])
    rng = _random.next_key() if opdef.need_rng else None
    from . import kernel_tier as _kernel_tier
    outputs, new_aux = _kernel_tier.dispatch(opdef, attrs, regular, aux,
                                             False, rng)
    ctx = inputs[0].context if inputs and isinstance(inputs[0], NDArray) \
        else current_context()
    # mutate-input ops (sgd_update etc.): swap new buffer into input handle
    if opdef.mutate_inputs:
        for mname, new_val in zip(opdef.mutate_inputs, outputs):
            idx = in_names.index(mname)
            if idx < len(inputs) and isinstance(inputs[idx], NDArray):
                inputs[idx]._set(new_val)
    if aux_n:
        for handle, new_val in zip(inputs[len(arrs) - aux_n:], new_aux):
            if isinstance(handle, NDArray):
                handle._set(new_val)
    results = [NDArray(o, ctx=ctx) for o in outputs]
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, results):
            dst._set(src.asjax())
        results = list(outs)
    # autograd tape (reference: RecordImperativeFCompute, autograd.cc:70)
    from . import autograd as _ag
    _ag._record(opdef, attrs, list(inputs), arrs, results, rng)
    if out is not None:
        return out
    if len(results) == 1:
        return results[0]
    return results


# ---------------------------------------------------------------------
# module-level arithmetic/comparison helpers (reference: ndarray.py's
# add/maximum/... — scalar-or-array dispatch over the broadcast ops)
def _binary_fn(jnp_op, name):
    def fn(lhs, rhs):
        a = lhs.asjax() if isinstance(lhs, NDArray) else lhs
        b = rhs.asjax() if isinstance(rhs, NDArray) else rhs
        ctx = lhs.context if isinstance(lhs, NDArray) else \
            rhs.context if isinstance(rhs, NDArray) else None
        out = jnp_op(a, b)
        if out.dtype == jnp.bool_:        # reference comparisons return
            out = out.astype(jnp.float32)  # 0/1 floats, not bools
        return NDArray(out, ctx=ctx)
    fn.__name__ = name
    fn.__doc__ = (f"Element-wise broadcasting ``{name}`` of scalar/array "
                  "operands (reference: ndarray.py module helpers).")
    return fn


add = _binary_fn(jnp.add, "add")
subtract = _binary_fn(jnp.subtract, "subtract")
multiply = _binary_fn(jnp.multiply, "multiply")
divide = _binary_fn(jnp.divide, "divide")
true_divide = _binary_fn(jnp.true_divide, "true_divide")
power = _binary_fn(jnp.power, "power")
maximum = _binary_fn(jnp.maximum, "maximum")
minimum = _binary_fn(jnp.minimum, "minimum")
equal = _binary_fn(jnp.equal, "equal")
not_equal = _binary_fn(jnp.not_equal, "not_equal")
greater = _binary_fn(jnp.greater, "greater")
greater_equal = _binary_fn(jnp.greater_equal, "greater_equal")
lesser = _binary_fn(jnp.less, "lesser")
lesser_equal = _binary_fn(jnp.less_equal, "lesser_equal")


def moveaxis(tensor, source, destination):
    """Move ``source`` axis to ``destination`` (reference: ndarray.py
    moveaxis)."""
    return NDArray(jnp.moveaxis(tensor.asjax(), source, destination),
                   ctx=tensor.context)


def onehot_encode(indices, out):
    """One-hot encode indices into ``out`` (reference: ndarray.py
    onehot_encode -> _internal._onehot_encode; depth = out.shape[1])."""
    depth = out.shape[1]
    idx = indices.asjax().astype(jnp.int32).ravel()
    out._set(jax.nn.one_hot(idx, depth, dtype=out.dtype))
    return out


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0,
             channels=3, mean=None):
    """Decode an image bytestring to a (H, W, C) float NDArray
    (reference: ndarray.py imdecode over the opencv plugin). With a
    batched ``out`` (N, H, W, C), writes slot ``index``."""
    from .image import _imdecode_np          # cv2-or-PIL, raises MXNetError
    img = _imdecode_np(np.frombuffer(str_img, dtype=np.uint8),
                       to_rgb=channels == 3)
    if channels == 1 and img.ndim == 3:
        img = img.mean(axis=2, keepdims=True)
    elif img.ndim == 2:
        img = img[:, :, None]
    x0, y0, x1, y1 = clip_rect
    if x1 > 0 and y1 > 0:
        img = img[y0:y1, x0:x1]
    img = img.astype(np.float32)
    if mean is not None:
        img = img - (mean.asnumpy() if isinstance(mean, NDArray)
                     else np.asarray(mean, np.float32))
    if out is not None:
        if out.ndim == img.ndim + 1:         # batched buffer: one slot
            out[index] = img
        else:
            out._set(jnp.asarray(img.reshape(out.shape), dtype=out.dtype))
        return out
    return NDArray(jnp.asarray(img))
