"""mx.rtc: user-supplied accelerator kernels at runtime.

Reference counterpart: ``mx.rtc.Rtc`` compiles CUDA C source through nvrtc
and pushes it onto NDArrays (reference: src/common/mxrtc.cc:1-141,
c_api.h:1471-1491, python/mxnet/rtc.py). The TPU has no user-facing
runtime-compiled C — the native kernel language is **Pallas** (Mosaic), so
here a "kernel" is a Python Pallas function compiled for the TPU at trace
time (interpret mode on CPU keeps kernels testable everywhere):

  * ``Rtc(name, inputs, outputs, kernel)`` — imperative push, API-shaped
    like the reference class;
  * ``register_pallas_op(...)`` — the deeper integration the reference
    never had: a user kernel becomes a first-class registry op, visible as
    ``mx.nd.<name>`` / ``mx.sym.<name>``, optionally differentiable via a
    user VJP kernel, and fusable into jitted executor graphs.

A built-in fused SGD-momentum update kernel doubles as the reference
implementation and the numerics test target (vs the XLA composition in
ops/optimizer_op.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .base import MXNetError
from .ops.registry import register as _register_op, OP_REGISTRY
# the production kernels (and the shared interpret-gated pallas_call)
# live in ops/pallas_kernels.py; rtc re-exports the public surfaces so
# the reference-shaped mx.rtc API is unchanged
from .ops.pallas_kernels import (pallas_call, _interpret,  # noqa: F401
                                 pallas_sgd_mom_update)

__all__ = ["Rtc", "register_pallas_op", "pallas_call",
           "pallas_sgd_mom_update", "flash_attention",
           "flash_attention_partial"]


class Rtc:
    """Imperative kernel handle (reference API: mx.rtc.Rtc(name, inputs,
    outputs, kernel); push(ins, outs, grid, block)).

    ``inputs``/``outputs`` are (name, NDArray) example pairs fixing
    shapes/dtypes like the reference; ``kernel`` is a Pallas kernel
    function taking one ref per input followed by one ref per output.
    Grid/block dims are Pallas grid/BlockSpecs — pass ``grid=`` if the
    kernel tiles; the default maps whole arrays into VMEM.
    """

    def __init__(self, name, inputs, outputs, kernel, grid=None,
                 in_specs=None, out_specs=None):
        self.name = name
        self._in_shapes = [(nm, tuple(a.shape), a.dtype)
                           for nm, a in inputs]
        self._out_struct = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                            for _, a in outputs]
        kwargs = {}
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = in_specs
        if out_specs is not None:
            kwargs["out_specs"] = out_specs
        self._fn = jax.jit(pallas_call(kernel, out_shape=self._out_struct,
                                       **kwargs))

    def push(self, ins, outs, grid_dims=None, block_dims=None):
        """Run the kernel. grid/block dims are fixed at construction in
        Pallas (they shape the compiled program); passing different ones
        here raises, matching the spirit of the reference's checks."""
        if grid_dims is not None or block_dims is not None:
            raise MXNetError("Pallas grids are fixed at Rtc construction; "
                             "rebuild the Rtc to change tiling")
        if len(ins) != len(self._in_shapes):
            raise MXNetError(f"{self.name}: expected "
                             f"{len(self._in_shapes)} inputs")
        if len(outs) != len(self._out_struct):
            raise MXNetError(f"{self.name}: expected "
                             f"{len(self._out_struct)} outputs, "
                             f"got {len(outs)}")
        vals = [a.asjax() for a in ins]
        for v, (nm, shp, dt) in zip(vals, self._in_shapes):
            if tuple(v.shape) != shp:
                raise MXNetError(f"{self.name}: input {nm!r} shape "
                                 f"{v.shape} != declared {shp}")
        for i, (o, st) in enumerate(zip(outs, self._out_struct)):
            if tuple(o.shape) != tuple(st.shape):
                raise MXNetError(f"{self.name}: output {i} shape "
                                 f"{tuple(o.shape)} != declared "
                                 f"{tuple(st.shape)}")
        results = self._fn(*vals)
        if not isinstance(results, (list, tuple)):
            results = [results]
        for dst, r in zip(outs, results):
            dst._set(r)
        return outs


def register_pallas_op(name, kernel, out_shapes, inputs=("data",),
                       vjp_kernel=None, grid=None, in_specs=None,
                       out_specs=None, vjp_grid=None, vjp_in_specs=None,
                       vjp_out_specs=None, attr_spec=None,
                       reference=None):
    """Register a Pallas kernel as a graph operator.

    Parameters
    ----------
    kernel : fn(attrs) -> pallas kernel fn(*in_refs, *out_refs). Attrs are
        closed over so hyper-parameters stay compile-time scalars.
    out_shapes : fn(attrs, in_shapes) -> list of (shape, dtype-str|None);
        None dtype inherits input 0's dtype.
    vjp_kernel : optional fn(attrs) -> pallas kernel for the backward:
        fn(*in_refs, *cotangent_refs, *grad_refs). When given, the op is
        differentiable and the executor's jax.vjp sees a custom_vjp.
    grid / in_specs / out_specs : tiling for the forward call; each may be
        a value or fn(attrs, in_shapes). A tiled op MUST also tile its
        backward: vjp_grid/vjp_in_specs/vjp_out_specs (the vjp kernel's
        inputs are *vals + *cotangents, outputs one grad per input);
        omitting them for a gridded forward raises at registration.
    reference : optional XLA composition ``fn(attrs, *inputs) -> out``
        with the kernel's exact semantics. When given, the op registers
        with the reference as its ``forward`` and the Pallas kernel as
        the ``variants["pallas"]`` alternative — the SAME fallback +
        numerics-gate codepath the built-in production kernels use
        (kernel_tier.py): ``MXNET_KERNEL_TIER=xla`` forces the
        reference, ``auto`` autotunes per shape on TPU, and
        ``kernel_tier.numerics_gate`` can verify the pair. Without a
        reference the Pallas kernel is the only implementation and runs
        under every tier (interpret mode off-TPU).
    """
    if vjp_kernel is not None and grid is not None and vjp_grid is None:
        raise MXNetError(
            f"pallas op {name!r}: forward is tiled (grid=...) but the vjp "
            "has no vjp_grid — a whole-array backward would overflow VMEM "
            "or misread tile-shaped refs; pass vjp_grid/vjp_in_specs/"
            "vjp_out_specs")

    def _resolve(spec, attrs, in_shapes):
        return spec(attrs, in_shapes) if callable(spec) else spec

    def _build_call(attrs, in_vals):
        in_shapes = [tuple(v.shape) for v in in_vals]
        outs = []
        for shp, dt in out_shapes(attrs, in_shapes):
            outs.append(jax.ShapeDtypeStruct(
                tuple(shp), np.dtype(dt) if dt else in_vals[0].dtype))
        kwargs = {}
        for k, spec in (("grid", grid), ("in_specs", in_specs),
                        ("out_specs", out_specs)):
            if spec is not None:
                kwargs[k] = _resolve(spec, attrs, in_shapes)
        return pallas_call(kernel(attrs), out_shape=outs, **kwargs), outs

    # cache compiled callables per (attrs, input shapes/dtypes): eager
    # call sites would otherwise re-trace the kernel (and rebuild the
    # custom_vjp wrapper) on every invocation
    _cache = {}

    def _cache_key(attrs, in_vals):
        try:
            akey = tuple(sorted(attrs.items()))
            hash(akey)
        except TypeError:
            return None
        return (akey, tuple((tuple(v.shape), str(v.dtype))
                            for v in in_vals))

    def _make_op(attrs):
        if vjp_kernel is None:
            def op(*vals):
                call, _ = _build_call(attrs, vals)
                out = call(*vals)
                return tuple(out) if isinstance(out, (list, tuple)) else out
            return op

        @jax.custom_vjp
        def op(*vals):
            call, _ = _build_call(attrs, vals)
            out = call(*vals)
            return tuple(out) if isinstance(out, (list, tuple)) else out

        def fwd(*vals):
            return op(*vals), vals

        def bwd(vals, cts):
            if not isinstance(cts, (list, tuple)):
                cts = (cts,)
            in_shapes = [tuple(v.shape) for v in vals]
            grads_struct = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                            for v in vals]
            kwargs = {}
            for k, spec in (("grid", vjp_grid),
                            ("in_specs", vjp_in_specs),
                            ("out_specs", vjp_out_specs)):
                if spec is not None:
                    kwargs[k] = _resolve(spec, attrs, in_shapes)
            bw = pallas_call(vjp_kernel(attrs), out_shape=grads_struct,
                             **kwargs)
            return tuple(bw(*vals, *cts))

        op.defvjp(fwd, bwd)
        return op

    def simple_forward(attrs, *in_vals):
        key = _cache_key(attrs, in_vals)
        op = _cache.get(key) if key is not None else None
        if op is None:
            op = jax.jit(_make_op(attrs))
            if key is not None:
                _cache[key] = op
        return op(*in_vals)

    if reference is None:
        return _register_op(name, inputs=inputs, simple=simple_forward,
                            attr_spec=attr_spec or {})

    # with a reference composition, the user kernel rides the SAME
    # variants/tier mechanism as the built-in production kernels
    def pallas_variant(attrs, in_list, aux, is_train, rng):
        out = simple_forward(attrs, *in_list)
        if isinstance(out, (tuple, list)):
            return list(out), []
        return [out], []

    return _register_op(name, inputs=inputs, simple=reference,
                        attr_spec=attr_spec or {},
                        variants={"pallas": pallas_variant})


# --------------------------------------------------------------------------
# built-in: fused SGD-momentum update (the reference ships this fused on
# the GPU as sgd_mom_update, optimizer_op.cc:17-60). The kernel itself is
# PROMOTED to ops/pallas_kernels.py as a production variant of the
# sgd_mom_update registry op; this public op name keeps the explicit
# surface — forward is the XLA composition, the Pallas kernel rides the
# variants table, so MXNET_KERNEL_TIER selects per backend/shape like
# every other tiered op. Same convention as ops/optimizer_op.py:
# g = wd*w + clip(rescale*grad); mom' = momentum*mom - lr*g;
# weight' = weight + mom'.
# --------------------------------------------------------------------------
def _register_builtin():
    if "pallas_sgd_mom_update" in OP_REGISTRY:
        return

    def _hyper(attrs):
        return dict(
            lr=float(attrs["lr"]),
            momentum=float(attrs.get("momentum", 0.0)),
            wd=float(attrs.get("wd", 0.0)),
            rescale_grad=float(attrs.get("rescale_grad", 1.0)),
            clip_gradient=attrs.get("clip_gradient"))

    def xla_forward(attrs, weight, grad, mom):
        h = _hyper(attrs)
        g = grad * h["rescale_grad"]
        if h["clip_gradient"] is not None and \
                float(h["clip_gradient"]) > 0:
            c = float(h["clip_gradient"])
            g = jnp.clip(g, -c, c)
        g = g + h["wd"] * weight
        new_m = h["momentum"] * mom - h["lr"] * g
        return weight + new_m, new_m

    def pallas_variant(attrs, inputs, aux, is_train, rng):
        w, g, m = inputs
        return list(pallas_sgd_mom_update(w, g, m, **_hyper(attrs))), []

    # 3 inputs + 2 outputs resident as (256, 128) f32 tiles
    kspec = {"tiles": [((256, 128), "float32")] * 5,
             "dtypes": ("float32", "bfloat16", "float16")}
    _register_op("pallas_sgd_mom_update",
                 inputs=("weight", "grad", "mom"),
                 simple=xla_forward, num_outputs=2,
                 output_names=["weight_out", "mom_out"],
                 attr_spec={"lr": (float, None),
                            "momentum": (float, 0.0),
                            "wd": (float, 0.0),
                            "rescale_grad": (float, 1.0),
                            "clip_gradient": (lambda v: float(v), None)},
                 variants={"pallas": (pallas_variant, None, kspec)})


_register_builtin()


# --------------------------------------------------------------------------
# built-in: flash attention (the framework's marquee Pallas kernel — the
# reference's attention-era gap filled TPU-first). Forward is a Pallas
# online-softmax kernel on a (batch*heads, q blocks, k blocks) grid: K/V
# are tiled *through the grid* so VMEM only ever holds one
# (block, D) tile of each (running max/normalizer/accumulator persist in
# VMEM scratch across the sequential k dimension). Backward recomputes
# attention via the XLA composition under jax.custom_vjp (flash recompute
# strategy — no T x T tensor is ever stored for fwd). ``partial=True``
# returns the *unnormalized* (acc, m, l) triple instead, which is what
# ring attention (parallel/ring_attention.py) folds into its cross-device
# online-softmax carry — the kernel is the local block of the ring.
# --------------------------------------------------------------------------
def _flash_kernel(block_q, block_k, causal, scale, partial=False):
    def kernel(offs_ref, q_ref, k_ref, v_ref, *refs):
        # offs_ref: scalar-prefetch (2,) int32 — absolute sequence offsets
        # of this q shard and k shard (zero for self-attention; ring-step
        # shard offsets in partial mode, where device order = seq order)
        if partial:
            o_ref, m_ref, l_ref, m_s, l_s, acc_s = refs
        else:
            o_ref, m_s, l_s, acc_s = refs
        qi = pl.program_id(1)
        kb = pl.program_id(2)
        n_kb = pl.num_programs(2)

        @pl.when(kb == 0)
        def _init():
            m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
            l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
            acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

        q_start = offs_ref[0] + qi * block_q
        k_start = offs_ref[1] + kb * block_k

        def update():
            q = q_ref[...].astype(jnp.float32) * scale
            k = k_ref[...].astype(jnp.float32)
            v = v_ref[...].astype(jnp.float32)
            # HIGHEST: match the XLA composition's f32 accumulation (the
            # default would multiply in bf16 on the MXU)
            s = jnp.dot(q, k.T, precision=jax.lax.Precision.HIGHEST)
            if causal:
                q_pos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = k_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
            m = m_s[...]                       # (block_q, 1) f32
            m_blk = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_blk)
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - m_safe)
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
            m_s[...] = m_new
            l_s[...] = l_s[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_s[...] = acc_s[...] * corr + jnp.dot(
                p, v, precision=jax.lax.Precision.HIGHEST)

        if causal:
            # K blocks wholly above the diagonal contribute nothing —
            # skip their FLOPs instead of exp(-inf)-ing them
            pl.when(k_start <= q_start + block_q - 1)(update)
        else:
            update()

        @pl.when(kb == n_kb - 1)
        def _emit():
            if partial:
                o_ref[...] = acc_s[...].astype(o_ref.dtype)
                m_ref[...] = m_s[...]
                l_ref[...] = l_s[...]
            else:
                l = jnp.maximum(l_s[...], 1e-30)
                o_ref[...] = (acc_s[...] / l).astype(o_ref.dtype)
    return kernel


def _flash_call(qf, kf, vf, q_off, k_off, causal, scale, block_q, block_k,
                partial=False):
    """Launch the flash kernel on flattened (BH, T, D) operands.

    Returns the normalized output, or in partial mode the unnormalized
    (acc, m, l) with m/l shaped (BH, Tq, 1) float32.
    """
    from jax.experimental.pallas import tpu as pltpu

    BH, Tq, D = qf.shape
    Tk = kf.shape[1]
    grid = (BH, Tq // block_q, Tk // block_k)
    # index maps take the grid ids plus the scalar-prefetch ref (unused)
    in_specs = [
        pl.BlockSpec((None, block_q, D), lambda b, i, j, offs: (b, i, 0)),
        pl.BlockSpec((None, block_k, D), lambda b, i, j, offs: (b, j, 0)),
        pl.BlockSpec((None, block_k, D), lambda b, i, j, offs: (b, j, 0)),
    ]
    o_spec = pl.BlockSpec((None, block_q, D), lambda b, i, j, offs: (b, i, 0))
    ml_spec = pl.BlockSpec((None, block_q, 1), lambda b, i, j, offs: (b, i, 0))
    scratch = [pltpu.VMEM((block_q, 1), jnp.float32),
               pltpu.VMEM((block_q, 1), jnp.float32),
               pltpu.VMEM((block_q, D), jnp.float32)]
    # under shard_map (ring attention) outputs vary over the same mesh
    # axes as the operands — propagate vma so check_vma stays on
    try:
        vma = (jax.typeof(qf).vma | jax.typeof(kf).vma
               | jax.typeof(vf).vma)
    except (AttributeError, TypeError):
        vma = None

    def _struct(shape, dtype):
        if vma is not None:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        return jax.ShapeDtypeStruct(shape, dtype)

    if partial:
        out_shape = [_struct((BH, Tq, D), jnp.float32),
                     _struct((BH, Tq, 1), jnp.float32),
                     _struct((BH, Tq, 1), jnp.float32)]
        out_specs = [o_spec, ml_spec, ml_spec]
    else:
        out_shape = _struct((BH, Tq, D), qf.dtype)
        out_specs = o_spec
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=grid, in_specs=in_specs,
        out_specs=out_specs, scratch_shapes=scratch)
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    if vma:
        # match the tensor operands' varying axes (pallas requires all
        # operands to agree under shard_map's check_vma)
        missing = tuple(vma - jax.typeof(offs).vma)
        if missing:
            offs = jax.lax.pvary(offs, missing)
    return pallas_call(
        _flash_kernel(block_q, block_k, causal, scale, partial),
        out_shape=out_shape, grid_spec=grid_spec)(offs, qf, kf, vf)


def flash_attention_partial(q, k, v, q_off, k_off, causal=False,
                            block_q=128, block_k=128, scale=None):
    """Unnormalized flash attention block for ring composition.

    q: (B, H, Tq, D) local query shard; k/v: (B, H, Tk, D) the K/V shard
    currently held. ``q_off``/``k_off`` are the shards' absolute sequence
    offsets (traced values are fine — they ride the kernel's scalar
    prefetch). Returns (acc, m, l): acc (B,H,Tq,D) f32 unnormalized,
    m/l (B,H,Tq) f32 running max / normalizer — exactly the carry terms
    of the online softmax, mergeable across shards.
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k:
        raise MXNetError("flash_attention_partial: T must divide blocks")
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    acc, m, l = _flash_call(
        q.reshape(B * H, Tq, D), k.reshape(B * H, Tk, D),
        v.reshape(B * H, Tk, D), q_off, k_off, causal, scale,
        block_q, block_k, partial=True)
    return (acc.reshape(B, H, Tq, D), m.reshape(B, H, Tq),
            l.reshape(B, H, Tq))


def flash_attention(q, k, v, causal=False, block_q=128, block_k=128):
    """Pallas flash attention. q/k/v: (B, H, T, D) -> (B, H, T, D).

    Differentiable: backward recomputes standard attention (XLA) under
    custom_vjp, so training numerics match ``parallel.ring_attention
    .attention`` while forward never materializes the (T, T) matrix.
    """
    from .parallel.ring_attention import attention as _xla_attention

    B, H, T, D = q.shape
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if T % block_q or T % block_k:
        raise MXNetError(f"flash_attention: T={T} must be a multiple of "
                         f"block sizes ({block_q}, {block_k})")
    scale = 1.0 / float(np.sqrt(D))

    @jax.custom_vjp
    def _flash(q, k, v):
        out = _flash_call(
            q.reshape(B * H, T, D), k.reshape(B * H, T, D),
            v.reshape(B * H, T, D), 0, 0, causal, scale, block_q, block_k)
        return out.reshape(B, H, T, D)

    def fwd(q, k, v):
        return _flash(q, k, v), (q, k, v)

    def bwd(res, ct):
        q, k, v = res
        _, vjp_fn = jax.vjp(
            lambda q, k, v: _xla_attention(q, k, v, causal=causal), q, k, v)
        return vjp_fn(ct)

    _flash.defvjp(fwd, bwd)
    return _flash(q, k, v)


def _attention_xla_forward(attrs, q, k, v):
    # the exact composition the flash kernel is gated against —
    # VERDICT §5 measured flash both beating and losing to this,
    # which is precisely why the tier autotunes instead of trusting
    # the kernel's name
    from .base import parse_bool
    from .parallel.ring_attention import attention as xla_attention
    return xla_attention(q, k, v,
                         causal=parse_bool(attrs.get("causal", False)))


def _attention_pallas_variant(attrs, inputs, aux, is_train, rng):
    from .base import parse_bool
    q, k, v = inputs
    out = flash_attention(q, k, v,
                          causal=parse_bool(attrs.get("causal",
                                                      False)),
                          block_q=int(attrs.get("block_q", 128)),
                          block_k=int(attrs.get("block_k", 128)))
    return [out], []


def _attention_eligible(attrs, in_shapes, in_dtypes):
    if len(in_shapes[0]) != 4:
        return False
    t = in_shapes[0][2]
    if in_shapes[0][3] > 512:
        # q/k/v/acc blocks keep whole head rows in VMEM — the declared
        # _ATTENTION_KSPEC tile bound (PK901's eligibility side)
        return False
    bq = min(int(attrs.get("block_q", 128)), t)
    bk = min(int(attrs.get("block_k", 128)), t)
    return t % bq == 0 and t % bk == 0


_ATTENTION_ATTRS = {"causal": (None, False),
                    "block_q": (int, 128),
                    "block_k": (int, 128)}

#: q/k/v blocks plus the f32 accumulator at the d <= 512 bound
_ATTENTION_KSPEC = {
    "tiles": [((128, 512), "float32")] * 4,
    "dtypes": ("float32", "bfloat16", "float16"),
}


def _register_flash():
    if "pallas_flash_attention" in OP_REGISTRY:
        return
    _register_op("pallas_flash_attention", inputs=("q", "k", "v"),
                 simple=_attention_xla_forward,
                 attr_spec=dict(_ATTENTION_ATTRS),
                 variants={"pallas": (_attention_pallas_variant,
                                      _attention_eligible,
                                      _ATTENTION_KSPEC)})


def _attention_ring_variant(attrs, inputs, aux, is_train, rng):
    """Sequence-sharded lowering: ring attention over the active
    SpmdPlan's ``seq`` mesh axis (parallel/ring_attention.py — K/V
    shards rotate over ``lax.ppermute``, flash-style online softmax).
    Runs inside ``kernel_tier``'s plan_scope, so the mesh and axis
    names come from the binding's plan; the shard_map composes inside
    the jitted program and XLA partitions everything around it."""
    import functools
    from .base import parse_bool
    from .parallel import spmd as _spmd
    from .parallel.collectives import shard_map as _shard_map
    from .parallel.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    plan = _spmd.active_plan()
    if plan is None:
        raise MXNetError("attention ring variant dispatched without an "
                         "active SpmdPlan (kernel_tier arms the scope)")
    q, k, v = inputs
    causal = parse_bool(attrs.get("causal", False))
    seq_ax = plan.seq_axis
    batch_ax = plan.data_axis if (plan.n_data_shards() > 1 and
                                  q.shape[0] % plan.n_data_shards() == 0) \
        else None
    spec = P(batch_ax, None, seq_ax, None)
    run = _shard_map(
        functools.partial(ring_attention, axis_name=seq_ax, causal=causal),
        mesh=plan.mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return [run(q, k, v)], []


def _attention_ring_eligible(attrs, in_shapes, in_dtypes):
    """Eligible only under an active seq-sharded plan whose shard counts
    divide (B, T); self-attention shapes only (q/k/v agree)."""
    from .parallel import spmd as _spmd
    plan = _spmd.active_plan()
    if plan is None:
        return False
    n_seq = plan.n_seq_shards()
    if n_seq <= 1:
        return False
    if len(in_shapes) < 3 or len(in_shapes[0]) != 4:
        return False
    if not (tuple(in_shapes[0]) == tuple(in_shapes[1])
            == tuple(in_shapes[2])):
        return False
    b, _h, t, _d = in_shapes[0]
    if t < n_seq or t % n_seq:
        return False
    nd = plan.n_data_shards()
    return not (nd > 1 and b % nd)


def _register_attention():
    """``attention``: the graph-level attention OpDef the transformer
    workload (ROADMAP 1) binds, with THREE gated lowerings:

    * ``xla`` — the exact composition (``parallel.ring_attention
      .attention``), always present, always correct;
    * ``pallas`` — the flash kernel (fused lowering), numerics-gated and
      autotuned per shape by kernel_tier on TPU;
    * ``ring`` — the sequence-sharded lowering: when the binding's
      SpmdPlan carries a nonempty ``seq`` mesh axis, the op lowers to
      ring attention over ``lax.ppermute`` (kernel_tier selects it from
      the plan; ``MXNET_KERNEL_TIER=xla`` still forces the composition).
    """
    if "attention" in OP_REGISTRY:
        return
    _register_op("attention", inputs=("q", "k", "v"),
                 simple=_attention_xla_forward,
                 shape_passthrough=True,
                 attr_spec=dict(_ATTENTION_ATTRS),
                 variants={"pallas": (_attention_pallas_variant,
                                      _attention_eligible,
                                      _ATTENTION_KSPEC),
                           "ring": (_attention_ring_variant,
                                    _attention_ring_eligible)})


# --------------------------------------------------------------------------
# attention_decode: the KV-cache inference path. The cache is op AUX
# state carried through the executor (fixed capacity, f32/compute-width
# K/V arrays + an int32 cursor), read AND written on inference forwards
# (OpDef.stateful_infer) — N incremental single-token steps reproduce
# the length-N full-sequence forward.
#
# Two cursor layouts, one op:
#
# * scalar (default) — ONE (1,) cursor: all B rows decode the same
#   sequence position (the single-session KVCacheDecoder path);
# * ``per_slot=True`` — a (B, 1) int32 cursor VECTOR: each batch row is
#   an independent decode *slot* at its own position in its own slice
#   of the slot-pooled (B, H, C, Dh) cache. S=1 writes land per slot
#   through a one-hot select (bit-exact: untouched positions keep their
#   cache value verbatim); S>1 windows (chunked prefill, speculative
#   verify) land through a per-row dynamic_update_slice. The causal
#   mask is per slot AND per window offset
#   (key_pos <= cursor[b] + s), and the softmax runs over each slot's
#   own prefix — so ONE pinned program advances B independent staggered
#   sequences by S tokens per dispatch. A retired slot keeps advancing harmlessly
#   (its row is garbage nobody reads); rejoining resets only the
#   cursor, because positions beyond a slot's prefix are exp(-inf)-
#   masked to exactly zero weight and every attended position has been
#   rewritten by the new sequence before its first read — slot reuse is
#   bit-clean without touching the cache rows.
# --------------------------------------------------------------------------
def _decode_check_overflow(pos, S, capacity, per_slot):
    """Overflow raises cleanly whenever the cursor is concrete (eager
    dispatch); jitted paths enforce it host-side via the decode driver
    (models.transformer.KVCacheDecoder) — dynamic_update_slice would
    otherwise silently clamp the write."""
    if isinstance(pos, jax.core.Tracer):
        return
    if per_slot:
        over = [int(i) for i in np.nonzero(
            np.asarray(pos) + S > capacity)[0]]
        if over:
            raise MXNetError(
                f"attention_decode: cache overflow in slot(s) {over} "
                f"(cursor + {S} > capacity {capacity}); retire the "
                "sequence or re-bind with a larger capacity=")
    elif int(pos) + S > capacity:
        raise MXNetError(
            f"attention_decode: cache overflow (pos {int(pos)} + {S} new "
            f"tokens > capacity {capacity}); re-bind with a larger "
            "capacity= or reset the cache")


def _decode_rope_write(attrs, q, k, v, k_cache, v_cache, pos, per_slot):
    """RoPE + cache write, shared verbatim by the XLA composition and
    the Pallas decode variant (the kernel only replaces the attention
    read) — so the write semantics stay bit-identical across tiers.
    ``pos`` is a scalar (single-session) or a (B,) vector (slot pool).
    Returns the rotated q and the updated caches."""
    from .base import parse_bool, parse_float
    from .ops.nn import rope_apply

    B, H, S, Dh = q.shape
    capacity = k_cache.shape[2]
    if parse_bool(attrs.get("rope", False)):
        base = parse_float(attrs.get("rope_base", 10000.0))
        if per_slot:
            positions = pos[:, None] + jnp.arange(S)[None, :]   # (B, S)
        else:
            positions = pos + jnp.arange(S)
        q = rope_apply(q, positions, base)
        k = rope_apply(k, positions, base)
    if not per_slot:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, pos, 0))
    elif S == 1:
        # one-hot per-slot write: jnp.where keeps untouched cache
        # positions bit-identical and lands each slot's token at its own
        # cursor; a cursor past capacity matches nothing (no clamped
        # write). Kept verbatim for S=1 so the steady-state decode
        # program stays bit-identical to the pre-window pin.
        key_pos = jnp.arange(capacity)                         # (C,)
        write = (key_pos[None, :] == pos[:, None])[:, None, :, None]
        k_cache = jnp.where(write, k.astype(k_cache.dtype), k_cache)
        v_cache = jnp.where(write, v.astype(v_cache.dtype), v_cache)
    else:
        # window write: each slot lands its S rows at its own cursor.
        # vmap over B means a slot only ever writes its OWN cache row,
        # so the clamp DUS applies near capacity can't corrupt a
        # batchmate — the driver guards pos + S <= capacity for every
        # slot that is still live.
        def _write_row(cache_row, new_row, p):
            return jax.lax.dynamic_update_slice(cache_row, new_row,
                                                (0, p, 0))
        k_cache = jax.vmap(_write_row)(k_cache,
                                       k.astype(k_cache.dtype), pos)
        v_cache = jax.vmap(_write_row)(v_cache,
                                       v.astype(v_cache.dtype), pos)
    return q, k_cache, v_cache


def _attention_decode_fwd(attrs, inputs, aux, is_train, rng):
    from .base import parse_bool

    q, k, v = inputs                       # (B, H, S, Dh), S new tokens
    k_cache, v_cache, cursor = aux         # (B,H,C,Dh) x2 + cursor
    if is_train:
        raise MXNetError("attention_decode is an inference op (train "
                         "with the full-sequence `attention` graph)")
    if parse_bool(attrs.get("per_slot", False)):
        return _attention_decode_per_slot(attrs, q, k, v, k_cache,
                                          v_cache, cursor)
    B, H, S, Dh = q.shape
    capacity = k_cache.shape[2]
    pos = cursor.reshape(()).astype(jnp.int32)
    _decode_check_overflow(pos, S, capacity, per_slot=False)
    scale = 1.0 / float(np.sqrt(Dh))
    q, k_cache, v_cache = _decode_rope_write(attrs, q, k, v, k_cache,
                                             v_cache, pos,
                                             per_slot=False)
    # same numerics shape as the full forward (ring_attention.attention):
    # f32 logits at HIGHEST precision, -inf causal mask, f32 softmax
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache.astype(q.dtype),
                        precision=jax.lax.Precision.HIGHEST,
                        preferred_element_type=jnp.float32) * scale
    key_pos = jnp.arange(capacity)[None, :]
    q_pos = (pos + jnp.arange(S))[:, None]
    mask = key_pos <= q_pos                           # (S, C)
    logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs,
                     v_cache.astype(jnp.float32),
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32)
    new_cursor = (pos + S).reshape((1,)).astype(jnp.int32)
    return [out.astype(q.dtype)], [k_cache, v_cache, new_cursor]


def _attention_decode_per_slot(attrs, q, k, v, k_cache, v_cache, cursor):
    """The slot-pooled lowering: cursor (B, 1), an S-token window per
    slot. S=1 is the steady-state decode program (one-hot cache write,
    bit-pinned since the slot pool landed); S>1 is the chunked-prefill /
    speculative-verify window — each slot writes its S tokens at its OWN
    cursor via a per-row ``dynamic_update_slice`` and the causal mask
    runs over ``cursor[b] + arange(S)``, so one pinned program advances
    B staggered sequences by S positions per dispatch."""
    B, H, S, Dh = q.shape
    capacity = k_cache.shape[2]
    pos = cursor.reshape((B,)).astype(jnp.int32)          # (B,)
    _decode_check_overflow(pos, S, capacity, per_slot=True)
    scale = 1.0 / float(np.sqrt(Dh))
    q, k_cache, v_cache = _decode_rope_write(attrs, q, k, v, k_cache,
                                             v_cache, pos, per_slot=True)
    key_pos = jnp.arange(capacity)                         # (C,)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache.astype(q.dtype),
                        precision=jax.lax.Precision.HIGHEST,
                        preferred_element_type=jnp.float32) * scale
    # per-slot causal mask: query s of slot b sits at stream position
    # cursor[b] + s and attends key_pos <= that — within-window
    # causality falls out of the same comparison
    q_pos = pos[:, None] + jnp.arange(S)[None, :]          # (B, S)
    mask = (key_pos[None, None, :] <= q_pos[:, :, None])[:, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs,
                     v_cache.astype(jnp.float32),
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32)
    new_cursor = (pos + S).reshape((B, 1)).astype(jnp.int32)
    return [out.astype(q.dtype)], [k_cache, v_cache, new_cursor]


def _attention_decode_pallas_variant(attrs, inputs, aux, is_train, rng):
    """Flash-decode lowering: RoPE + cache writes stay the exact shared
    XLA helpers (bit-identical cache contents across tiers); only the
    attention READ — the cache-bandwidth-bound part — runs the Pallas
    kernel (ops/pallas_kernels.decode_attention), whose scalar-prefetched
    cursor bounds the K/V blocks actually fetched from HBM to the live
    prefix ``[0, cursor_b + S)`` instead of the full capacity."""
    from .base import parse_bool
    from .ops.pallas_kernels import decode_attention

    q, k, v = inputs
    k_cache, v_cache, cursor = aux
    if is_train:
        raise MXNetError("attention_decode is an inference op (train "
                         "with the full-sequence `attention` graph)")
    B, H, S, Dh = q.shape
    capacity = k_cache.shape[2]
    per_slot = parse_bool(attrs.get("per_slot", False))
    if per_slot:
        pos = cursor.reshape((B,)).astype(jnp.int32)
        new_cursor = (pos + S).reshape((B, 1)).astype(jnp.int32)
    else:
        pos = cursor.reshape(()).astype(jnp.int32)
        new_cursor = (pos + S).reshape((1,)).astype(jnp.int32)
    _decode_check_overflow(pos, S, capacity, per_slot=per_slot)
    q, k_cache, v_cache = _decode_rope_write(attrs, q, k, v, k_cache,
                                             v_cache, pos,
                                             per_slot=per_slot)
    # the kernel is row-cursor uniform: the scalar layout is the
    # per-slot layout with every row at the same position
    pos_rows = pos if per_slot else jnp.broadcast_to(pos, (B,))
    out = decode_attention(q, k_cache, v_cache, pos_rows)
    return [out.astype(q.dtype)], [k_cache, v_cache, new_cursor]


def _attention_decode_eligible(attrs, in_shapes, in_dtypes):
    """Decode windows up to the declared kspec bounds: S <= 64 head
    rows resident, Dh <= 512, cache blocks tiling the capacity. The
    cache may be the compute width or an fp8 storage dtype (dequantized
    in-kernel on read). On a real TPU the head dim must be
    lane-aligned; interpret mode (off-TPU parity tests) takes any."""
    from .ops.pallas_kernels import _interpret
    if len(in_shapes) < 6 or len(in_shapes[0]) != 4 \
            or len(in_shapes[3]) != 4:
        return False
    b, h, s, dh = in_shapes[0]
    c = in_shapes[3][2]
    if s > 64 or dh > 512 or c < 1:
        return False
    if str(in_dtypes[0]) not in ("float32", "bfloat16", "float16"):
        return False
    if str(in_dtypes[3]) not in ("float32", "bfloat16", "float16",
                                 "float8_e4m3fn", "float8_e5m2"):
        return False
    return (dh % 128 == 0 and c % 128 == 0) or _interpret()


def _attention_decode_infer(attrs, in_shapes):
    from .base import parse_bool
    q_s = in_shapes[0]
    c = int(attrs.get("capacity", 256))
    per_slot = parse_bool(attrs.get("per_slot", False))
    if q_s is None:
        return in_shapes, [None], [None, None,
                                   None if per_slot else (1,)]
    b, h, _s, dh = q_s
    cache = (b, h, c, dh)
    cur = (b, 1) if per_slot else (1,)
    return [q_s, q_s, q_s], [q_s], [cache, cache, cur]


#: the S>1 window path (chunked prefill / speculative verify): q and
#: the f32 out accumulator hold one 64-token chunk of head rows while
#: two cache blocks stream K-major — declared and PK9xx-validated at
#: registration so the decode window variant is gated by the same
#: import-time contract as the Pallas kernels, even while its lowering
#: is the XLA composition
_ATTENTION_DECODE_KSPEC = {
    "tiles": [((64, 512), "float32"),      # q window (S=64 x Dh<=512)
              ((128, 512), "float32"),     # k_cache block
              ((128, 512), "float32"),     # v_cache block
              ((64, 512), "float32")],     # f32 out accumulator
    "dtypes": ("float32", "bfloat16", "float16"),
}

#: the flash-decode kernel's worst-case VMEM set at the eligibility
#: bounds (S<=64, Dh<=512, 128-row cache blocks): q + one K + one V
#: block + the f32 m/l/acc scratch + the out window. fp8 cache dtypes
#: are in the gate set — the kernel dequantizes storage rows on read.
_ATTENTION_DECODE_PALLAS_KSPEC = {
    "tiles": [((64, 512), "float32"),      # q window
              ((128, 512), "float32"),     # k_cache block
              ((128, 512), "float32"),     # v_cache block
              ((64, 512), "float32"),      # acc scratch
              ((64, 128), "float32"),      # m + l scratch (lane-padded)
              ((64, 512), "float32")],     # out window
    "dtypes": ("float32", "bfloat16", "float16",
               "float8_e4m3fn", "float8_e5m2"),
}

#: aliases accepted by the ``cache_dtype`` attr (fp8 KV storage)
_CACHE_DTYPE_ALIASES = {"fp8": "float8_e4m3fn",
                        "e4m3": "float8_e4m3fn",
                        "e5m2": "float8_e5m2"}


def _cache_dtype_of(attrs):
    """Resolve the declared KV-cache storage dtype, or None for the
    default (compute-width) cells. Used as a callable aux_dtypes entry
    so only non-default graphs stamp ``__dtype__`` on the cache cells —
    existing serialized graphs stay byte-identical."""
    val = str(attrs.get("cache_dtype", "") or "").strip()
    if not val:
        return None
    return _CACHE_DTYPE_ALIASES.get(val, val)


def _register_attention_decode():
    if "attention_decode" in OP_REGISTRY:
        return
    from .analysis.kernelcheck import validate_kernel_spec
    validate_kernel_spec("attention_decode", "window",
                         _ATTENTION_DECODE_KSPEC)
    _register_op("attention_decode", inputs=("q", "k", "v"),
                 aux=("k_cache", "v_cache", "cache_pos"),
                 full=_attention_decode_fwd,
                 stateful_infer=True,
                 aux_dtypes={"cache_pos": "int32",
                             "k_cache": _cache_dtype_of,
                             "v_cache": _cache_dtype_of},
                 infer_shape=_attention_decode_infer,
                 attr_spec={"capacity": (int, 256),
                            "rope": (None, False),
                            "rope_base": (float, 10000.0),
                            "per_slot": (None, False),
                            "cache_dtype": (str, "")},
                 variants={"pallas": (_attention_decode_pallas_variant,
                                      _attention_decode_eligible,
                                      _ATTENTION_DECODE_PALLAS_KSPEC)})


_register_flash()
_register_attention()
_register_attention_decode()

# rtc's ops register after ops/cost.py's import-time pass — re-seed so
# pallas_sgd_mom_update / pallas_flash_attention carry their estimators
from .ops import cost as _cost          # noqa: E402
_cost.seed_costs()
