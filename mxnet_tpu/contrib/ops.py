"""contrib operators (reference: src/operator/contrib/, 5.2k LoC).

ctc_loss (warp-ctc), fft/ifft (cuFFT), count_sketch, quantize/dequantize,
MultiBox{Prior,Target,Detection} (SSD), MultiProposal. All expressed as XLA
programs; the DP-heavy ones (CTC forward-backward, SSD matching) use
``lax.scan``/vectorized masks instead of CUDA kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import parse_tuple, parse_bool, parse_int, parse_float
from ..ops.registry import register, alias


# --------------------------------------------------------------------------
# quantize / dequantize (reference: contrib/quantize-inl.h)
# --------------------------------------------------------------------------
@register("_contrib_quantize", inputs=("data", "min_range", "max_range"),
          attr_spec={"out_type": (None, "uint8")}, num_outputs=3,
          output_names=["output", "min_output", "max_output"])
def _quantize(attrs, data, min_range, max_range):
    out_type = attrs.get("out_type", "uint8")
    info = np.iinfo(np.dtype(out_type))
    scale = (info.max - info.min) / (max_range - min_range)
    q = jnp.clip(jnp.round((data - min_range) * scale) + info.min,
                 info.min, info.max).astype(np.dtype(out_type))
    return q, min_range, max_range

alias("quantize", "_contrib_quantize")


@register("_contrib_dequantize", inputs=("data", "min_range", "max_range"),
          attr_spec={"out_type": (None, "float32")}, num_outputs=1)
def _dequantize(attrs, data, min_range, max_range):
    info = np.iinfo(np.dtype(data.dtype))
    scale = (max_range - min_range) / (info.max - info.min)
    return ((data.astype(jnp.float32) - info.min) * scale +
            min_range).astype(np.dtype(attrs.get("out_type", "float32")))

alias("dequantize", "_contrib_dequantize")


# --------------------------------------------------------------------------
# fft / ifft (reference: contrib/fft-inl.h over cuFFT; compute_size ignored
# — XLA schedules batched FFTs itself)
# --------------------------------------------------------------------------
@register("_contrib_fft", inputs=("data",),
          attr_spec={"compute_size": (parse_int, 128)})
def _fft(attrs, data):
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    # layout: interleaved real/imag along last axis (reference contract)
    ri = jnp.stack([out.real, out.imag], axis=-1)
    return ri.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)

alias("fft", "_contrib_fft")


@register("_contrib_ifft", inputs=("data",),
          attr_spec={"compute_size": (parse_int, 128)})
def _ifft(attrs, data):
    n = data.shape[-1] // 2
    ri = data.reshape(data.shape[:-1] + (n, 2))
    cplx = ri[..., 0] + 1j * ri[..., 1]
    out = jnp.fft.ifft(cplx, axis=-1) * n  # reference scales by n
    return out.real.astype(jnp.float32)

alias("ifft", "_contrib_ifft")


# --------------------------------------------------------------------------
# count_sketch (reference: contrib/count_sketch-inl.h)
# --------------------------------------------------------------------------
@register("_contrib_count_sketch", inputs=("data", "h", "s"),
          attr_spec={"out_dim": (parse_int, None),
                     "processing_batch_size": (parse_int, 32)})
def _count_sketch(attrs, data, h, s):
    out_dim = attrs["out_dim"]
    hh = h.reshape(-1).astype(jnp.int32) % out_dim
    ss = s.reshape(-1).astype(data.dtype)
    signed = data * ss[None, :]
    out = jnp.zeros((data.shape[0], out_dim), dtype=data.dtype)
    return out.at[:, hh].add(signed)

alias("count_sketch", "_contrib_count_sketch")


# --------------------------------------------------------------------------
# CTC loss (reference: contrib/ctc_loss-inl.h wrapping warp-ctc).
# Log-space forward algorithm via lax.scan over time.
# --------------------------------------------------------------------------
def _ctc_forward(log_probs, labels, input_len, label_len, blank=0):
    """alpha recursion for one sequence. log_probs (T, C), labels (L,)."""
    L = labels.shape[0]
    S = 2 * L + 1
    ext = jnp.full((S,), blank, dtype=jnp.int32)
    ext = ext.at[1::2].set(labels.astype(jnp.int32))
    neg_inf = -1e10

    can_skip = jnp.zeros((S,), dtype=bool)
    can_skip = can_skip.at[2:].set(
        (ext[2:] != blank) & (ext[2:] != ext[:-2]))

    alpha0 = jnp.full((S,), neg_inf)
    alpha0 = alpha0.at[0].set(log_probs[0, blank])
    alpha0 = alpha0.at[1].set(jnp.where(L > 0, log_probs[0, ext[1]],
                                        neg_inf))

    def step(alpha, lp):
        stay = alpha
        prev1 = jnp.concatenate([jnp.array([neg_inf]), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full((2,), neg_inf), alpha[:-2]])
        prev2 = jnp.where(can_skip, prev2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        new_alpha = merged + lp[ext]
        return new_alpha, new_alpha

    alphaT, alphas = lax.scan(step, alpha0, log_probs[1:])
    all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
    final = all_alphas[input_len.astype(jnp.int32) - 1]
    s_last = 2 * label_len.astype(jnp.int32)
    ll = jnp.logaddexp(final[s_last],
                       jnp.where(label_len > 0, final[s_last - 1], -1e10))
    return -ll


def _ctc_fwd_batch(data, label, data_lengths, label_lengths):
    """data (T, N, C) activations; label (N, L) with 0 = blank padding
    convention (reference uses 0-padded labels, blank=0 internally? the
    reference uses label value 0 as padding with use_*_lengths off)."""
    log_probs = jax.nn.log_softmax(data, axis=-1)
    T, N, C = data.shape

    def one(n):
        return _ctc_forward(log_probs[:, n], label[n],
                            data_lengths[n], label_lengths[n])
    return jax.vmap(one)(jnp.arange(N))


def _make_ctc():
    @jax.custom_vjp
    def ctc(data, label, dlen, llen):
        return _ctc_fwd_batch(data, label, dlen, llen)

    def fwd(data, label, dlen, llen):
        loss, vjp_fn = jax.vjp(
            lambda d: _ctc_fwd_batch(d, label, dlen, llen), data)
        return loss, (vjp_fn,)

    def bwd(res, g):
        (vjp_fn,) = res
        (gd,) = vjp_fn(g)
        return gd, None, None, None

    ctc.defvjp(fwd, bwd)
    return ctc


_CTC = _make_ctc()


def _ctc_inputs(attrs):
    names = ["data", "label"]
    if parse_bool(attrs.get("use_data_lengths", False)):
        names.append("data_lengths")
    if parse_bool(attrs.get("use_label_lengths", False)):
        names.append("label_lengths")
    return names


@register("_contrib_ctc_loss", inputs=_ctc_inputs, is_loss=True,
          attr_spec={"use_data_lengths": (parse_bool, False),
                     "use_label_lengths": (parse_bool, False),
                     "blank_label": (None, "first")})
def _ctc_loss(attrs, data, label, data_lengths=None, label_lengths=None):
    T, N, C = data.shape
    if data_lengths is None:
        data_lengths = jnp.full((N,), T, dtype=jnp.int32)
    if label_lengths is None:
        # 0-padded labels: effective length = count of non-zero entries
        label_lengths = jnp.sum((label != 0).astype(jnp.int32), axis=-1)
    return _CTC(data, label.astype(jnp.int32),
                data_lengths.astype(jnp.int32),
                label_lengths.astype(jnp.int32))

alias("ctc_loss", "_contrib_ctc_loss")
alias("CTCLoss", "_contrib_ctc_loss")


# --------------------------------------------------------------------------
# SSD MultiBox trio (reference: contrib/multibox_prior.cc,
# multibox_target.cc, multibox_detection.cc)
# --------------------------------------------------------------------------
def _parse_floats(val, default):
    if val is None:
        return default
    if isinstance(val, str):
        import ast
        val = ast.literal_eval(val)
    if isinstance(val, (int, float)):
        return (float(val),)
    return tuple(float(v) for v in val)


@register("MultiBoxPrior", inputs=("data",),
          attr_spec={"sizes": (lambda v: _parse_floats(v, (1.0,)), (1.0,)),
                     "ratios": (lambda v: _parse_floats(v, (1.0,)), (1.0,)),
                     "clip": (parse_bool, False),
                     "steps": (lambda v: _parse_floats(v, (-1.0, -1.0)),
                               (-1.0, -1.0)),
                     "offsets": (lambda v: _parse_floats(v, (0.5, 0.5)),
                                 (0.5, 0.5))})
def _multibox_prior(attrs, data):
    """Anchor generation. reference: multibox_prior-inl.h — per output
    pixel: |sizes| + |ratios| - 1 anchors (sizes with ratio 1, then extra
    ratios with size[0])."""
    h, w = data.shape[2], data.shape[3]
    sizes = attrs.get("sizes", (1.0,))
    ratios = attrs.get("ratios", (1.0,))
    steps = attrs.get("steps", (-1.0, -1.0))
    offsets = attrs.get("offsets", (0.5, 0.5))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cxg.ravel(), cyg.ravel()], axis=-1)  # (hw, 2)
    whs = []
    for s in sizes:
        whs.append((s * np.sqrt(ratios[0]), s / np.sqrt(ratios[0])))
    for r in ratios[1:]:
        whs.append((sizes[0] * np.sqrt(r), sizes[0] / np.sqrt(r)))
    whs = jnp.asarray(whs, dtype=jnp.float32)  # (A, 2) in (w, h)
    # account for aspect of the feature map (reference uses size relative
    # to the shorter side; keep w/h symmetric here)
    cxy = centers[:, None, :]
    half = whs[None, :, :] / 2.0
    boxes = jnp.concatenate([cxy - half, cxy + half], axis=-1)
    boxes = boxes.reshape(1, -1, 4)
    if parse_bool(attrs.get("clip", False)):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(jnp.float32)

alias("_contrib_MultiBoxPrior", "MultiBoxPrior")


def _iou(anchors, gt):
    """IoU matrix (A, 4) x (G, 4) -> (A, G), corner format."""
    ax1, ay1, ax2, ay2 = [anchors[:, i] for i in range(4)]
    gx1, gy1, gx2, gy2 = [gt[:, i] for i in range(4)]
    ix1 = jnp.maximum(ax1[:, None], gx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], gy1[None, :])
    ix2 = jnp.minimum(ax2[:, None], gx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], gy2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0)
    ih = jnp.maximum(iy2 - iy1, 0)
    inter = iw * ih
    a_area = (ax2 - ax1) * (ay2 - ay1)
    g_area = (gx2 - gx1) * (gy2 - gy1)
    union = a_area[:, None] + g_area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("MultiBoxTarget", inputs=("anchor", "label", "cls_pred"),
          attr_spec={"overlap_threshold": (parse_float, 0.5),
                     "ignore_label": (parse_float, -1.0),
                     "negative_mining_ratio": (parse_float, -1.0),
                     "negative_mining_thresh": (parse_float, 0.5),
                     "minimum_negative_samples": (parse_int, 0),
                     "variances": (lambda v: _parse_floats(
                         v, (0.1, 0.1, 0.2, 0.2)), (0.1, 0.1, 0.2, 0.2))},
          num_outputs=3,
          output_names=["loc_target", "loc_mask", "cls_target"])
def _multibox_target(attrs, anchor, label, cls_pred):
    """Anchor matching + target encoding. reference: multibox_target-inl.h.

    label: (N, num_obj, 5+) rows [cls, x1, y1, x2, y2], cls=-1 padding.
    """
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    thresh = attrs.get("overlap_threshold", 0.5)
    variances = attrs.get("variances", (0.1, 0.1, 0.2, 0.2))
    neg_ratio = parse_float(attrs.get("negative_mining_ratio", -1.0))
    ignore_label = parse_float(attrs.get("ignore_label", -1.0))
    min_neg = parse_int(attrs.get("minimum_negative_samples", 0))

    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    def one(lab, cp):
        valid = lab[:, 0] >= 0
        gt = lab[:, 1:5]
        ious = _iou(anchors, gt) * valid[None, :].astype(anchors.dtype)
        best_gt = jnp.argmax(ious, axis=1)
        best_iou = jnp.max(ious, axis=1)
        # force-match: each gt's best anchor is positive
        best_anchor = jnp.argmax(ious, axis=0)  # (G,)
        forced = jnp.zeros((A,), dtype=bool)
        forced = forced.at[best_anchor].set(valid)
        pos = (best_iou >= thresh) | forced
        matched_gt = gt[best_gt]
        gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
        gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
        gw = jnp.maximum(matched_gt[:, 2] - matched_gt[:, 0], 1e-8)
        gh = jnp.maximum(matched_gt[:, 3] - matched_gt[:, 1], 1e-8)
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / variances[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / variances[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)
        loc_t = loc_t * pos[:, None].astype(loc_t.dtype)
        loc_m = jnp.tile(pos[:, None].astype(loc_t.dtype), (1, 4))
        if neg_ratio > 0:
            # hard-negative mining (reference: multibox_target-inl.h
            # NegativeMining): candidates are anchors whose best IoU is
            # below negative_mining_thresh (moderate-overlap anchors stay
            # ignored); keep the ratio*|pos| candidates with the lowest
            # predicted background confidence, the rest get ignore_label
            # so SoftmaxOutput(use_ignore) skips them
            neg_thresh = parse_float(
                attrs.get("negative_mining_thresh", 0.5))
            neg_cand = (~pos) & (best_iou < neg_thresh)
            p = jax.nn.softmax(cp, axis=0)          # (C+1, A)
            hardness = jnp.where(neg_cand, -jnp.log(p[0] + 1e-12),
                                 -jnp.inf)
            order = jnp.argsort(-hardness)
            rank = jnp.zeros((A,), jnp.int32).at[order].set(
                jnp.arange(A, dtype=jnp.int32))
            n_neg = jnp.maximum(
                (neg_ratio * jnp.sum(pos)).astype(jnp.int32), min_neg)
            neg_sel = neg_cand & (rank < n_neg)
            cls_t = jnp.where(pos, lab[best_gt, 0] + 1.0,
                              jnp.where(neg_sel, 0.0, ignore_label))
        else:
            cls_t = jnp.where(pos, lab[best_gt, 0] + 1.0, 0.0)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_target, loc_mask, cls_target = jax.vmap(one)(label, cls_pred)
    return loc_target, loc_mask, cls_target

alias("_contrib_MultiBoxTarget", "MultiBoxTarget")


@register("MultiBoxDetection", inputs=("cls_prob", "loc_pred", "anchor"),
          attr_spec={"clip": (parse_bool, True),
                     "threshold": (parse_float, 0.01),
                     "background_id": (parse_int, 0),
                     "nms_threshold": (parse_float, 0.5),
                     "force_suppress": (parse_bool, False),
                     "variances": (lambda v: _parse_floats(
                         v, (0.1, 0.1, 0.2, 0.2)), (0.1, 0.1, 0.2, 0.2)),
                     "nms_topk": (parse_int, -1)})
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """Decode + per-class NMS. reference: multibox_detection-inl.h.
    Output (N, A, 6): [cls_id, score, x1, y1, x2, y2], cls_id=-1 pruned."""
    variances = attrs.get("variances", (0.1, 0.1, 0.2, 0.2))
    threshold = attrs.get("threshold", 0.01)
    nms_t = attrs.get("nms_threshold", 0.5)
    bg = attrs.get("background_id", 0)
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]

    def one(cp, lp):
        lp = lp.reshape(-1, 4)
        cx = lp[:, 0] * variances[0] * aw + acx
        cy = lp[:, 1] * variances[1] * ah + acy
        w = jnp.exp(lp[:, 2] * variances[2]) * aw / 2
        h = jnp.exp(lp[:, 3] * variances[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
        if parse_bool(attrs.get("clip", True)):
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        scores_all = cp  # (C, A)
        mask = jnp.arange(scores_all.shape[0]) != bg
        scores_nb = jnp.where(mask[:, None], scores_all, -1.0)
        cls_id = jnp.argmax(scores_nb, axis=0)
        score = jnp.max(scores_nb, axis=0)
        keep = score > threshold
        # greedy NMS via iterative suppression over sorted anchors
        order = jnp.argsort(-score)
        boxes_o = boxes[order]
        ious = _iou(boxes_o, boxes_o)
        same_cls = (cls_id[order][:, None] == cls_id[order][None, :]) | \
            parse_bool(attrs.get("force_suppress", False))
        suppress_mat = (ious > nms_t) & same_cls & \
            (jnp.arange(A)[:, None] > jnp.arange(A)[None, :])

        def body(i, alive):
            row = suppress_mat[:, i] & alive[i]
            return alive & ~row
        alive = lax.fori_loop(0, A, body,
                              jnp.ones((A,), dtype=bool))
        kept = keep[order] & alive
        # reported ids are 0-based with background removed
        # (reference: multibox_detection-inl.h TransformLocations)
        report_id = cls_id[order].astype(boxes.dtype) - \
            (cls_id[order] > bg).astype(boxes.dtype)
        out = jnp.concatenate([
            jnp.where(kept, report_id, -1.0)[:, None],
            (score[order] * kept)[:, None], boxes_o], axis=-1)
        return out

    return jax.vmap(one)(cls_prob, loc_pred)

alias("_contrib_MultiBoxDetection", "MultiBoxDetection")


# --------------------------------------------------------------------------
# MultiProposal (reference: contrib/multi_proposal.cc — batched RPN
# proposal generation for Faster-RCNN: anchors + bbox deltas -> clip ->
# min-size filter -> top-k by fg score -> NMS -> fixed-count RoIs)
# --------------------------------------------------------------------------
def _generate_base_anchors(stride, scales, ratios):
    """Standard RPN base anchors around the stride-sized cell at (0,0)."""
    base = np.array([0, 0, stride - 1, stride - 1], dtype=np.float64)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            sw, sh = ws * s, hs * s
            anchors.append([cx - 0.5 * (sw - 1), cy - 0.5 * (sh - 1),
                            cx + 0.5 * (sw - 1), cy + 0.5 * (sh - 1)])
    return np.array(anchors, dtype=np.float32)  # (A, 4)


def _iou_pixel(a, b):
    """Pairwise IoU with the pixel-inclusive (+1) area convention the
    reference RPN uses (multi_proposal.cc) — distinct from the normalized
    [0,1]-coordinate ``_iou`` used by the MultiBox family."""
    area_a = (a[:, 2] - a[:, 0] + 1.0) * (a[:, 3] - a[:, 1] + 1.0)
    area_b = (b[:, 2] - b[:, 0] + 1.0) * (b[:, 3] - b[:, 1] + 1.0)
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.clip(x2 - x1 + 1.0, 0.0, None)
    ih = jnp.clip(y2 - y1 + 1.0, 0.0, None)
    inter = iw * ih
    return inter / (area_a[:, None] + area_b[None, :] - inter)


def _mp_infer(attrs, in_shapes):
    cls_s = in_shapes[0]
    post = parse_int(attrs.get("rpn_post_nms_top_n", 300))
    n = cls_s[0] if cls_s is not None else None
    out = [(n * post, 5) if n is not None else None]
    if parse_bool(attrs.get("output_score", False)):
        out.append((n * post, 1) if n is not None else None)
    return list(in_shapes), out, []


@register("MultiProposal", inputs=("cls_prob", "bbox_pred", "im_info"),
          infer_shape=_mp_infer,
          num_outputs=lambda a: 2 if parse_bool(
              a.get("output_score", False)) else 1,
          attr_spec={
              "rpn_pre_nms_top_n": (parse_int, 6000),
              "rpn_post_nms_top_n": (parse_int, 300),
              "threshold": (parse_float, 0.7),
              "rpn_min_size": (parse_int, 16),
              "scales": (lambda v: _parse_floats(v, (4., 8., 16., 32.)),
                         (4., 8., 16., 32.)),
              "ratios": (lambda v: _parse_floats(v, (0.5, 1., 2.)),
                         (0.5, 1., 2.)),
              "feature_stride": (parse_int, 16),
              "output_score": (parse_bool, False),
              "iou_loss": (parse_bool, False)})
def _multi_proposal(attrs, cls_prob, bbox_pred, im_info):
    stride = attrs.get("feature_stride", 16)
    scales = attrs.get("scales", (4., 8., 16., 32.))
    ratios = attrs.get("ratios", (0.5, 1., 2.))
    nms_t = attrs.get("threshold", 0.7)
    min_size = attrs.get("rpn_min_size", 16)
    N, _, H, W = cls_prob.shape
    base = _generate_base_anchors(stride, scales, ratios)     # (A, 4)
    A = base.shape[0]
    sx = (jnp.arange(W) * stride).astype(jnp.float32)
    sy = (jnp.arange(H) * stride).astype(jnp.float32)
    syg, sxg = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([sxg, syg, sxg, syg], axis=-1)         # (H, W, 4)
    anchors = shifts[:, :, None, :] + jnp.asarray(base)       # (H, W, A, 4)
    anchors = anchors.reshape(-1, 4)                          # (HWA, 4)
    total = H * W * A
    pre = min(parse_int(attrs.get("rpn_pre_nms_top_n", 6000)), total)
    post = parse_int(attrs.get("rpn_post_nms_top_n", 300))

    def one(cp, bp, info):
        # fg scores: channels [A:2A); layout (A, H, W) -> (H, W, A)
        score = cp[A:].transpose(1, 2, 0).reshape(-1)
        deltas = bp.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        if parse_bool(attrs.get("iou_loss", False)):
            # IoU-loss decoding: deltas are corner offsets
            # (reference multi_proposal.cc IoUTransformInv)
            boxes = anchors + deltas
        else:
            aw = anchors[:, 2] - anchors[:, 0] + 1.0
            ah = anchors[:, 3] - anchors[:, 1] + 1.0
            acx = anchors[:, 0] + 0.5 * (aw - 1.0)
            acy = anchors[:, 1] + 0.5 * (ah - 1.0)
            cx = deltas[:, 0] * aw + acx
            cy = deltas[:, 1] * ah + acy
            w = jnp.exp(deltas[:, 2]) * aw
            h = jnp.exp(deltas[:, 3]) * ah
            boxes = jnp.stack([cx - 0.5 * (w - 1.0), cy - 0.5 * (h - 1.0),
                               cx + 0.5 * (w - 1.0), cy + 0.5 * (h - 1.0)],
                              axis=-1)
        im_h, im_w, im_scale = info[0], info[1], info[2]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0.0, im_w - 1.0),
                           jnp.clip(boxes[:, 1], 0.0, im_h - 1.0),
                           jnp.clip(boxes[:, 2], 0.0, im_w - 1.0),
                           jnp.clip(boxes[:, 3], 0.0, im_h - 1.0)], axis=-1)
        ms = min_size * im_scale
        bw = boxes[:, 2] - boxes[:, 0] + 1.0
        bh = boxes[:, 3] - boxes[:, 1] + 1.0
        valid = (bw >= ms) & (bh >= ms)
        score_m = jnp.where(valid, score, -jnp.inf)
        top_scores, top_idx = lax.top_k(score_m, pre)
        top_boxes = boxes[top_idx]
        ious = _iou_pixel(top_boxes, top_boxes)
        upper = jnp.arange(pre)[:, None] > jnp.arange(pre)[None, :]
        suppress = (ious > nms_t) & upper

        def body(i, alive):
            return alive & ~(suppress[:, i] & alive[i])
        alive = lax.fori_loop(0, pre, body, jnp.ones((pre,), dtype=bool))
        alive = alive & jnp.isfinite(top_scores)
        # stable-compact the survivors to the front, pad with box 0
        gather = _compact_indices(alive, pre, post)
        out_boxes = top_boxes[gather]
        gathered = top_scores[gather]
        out_scores = jnp.where((jnp.arange(post) < jnp.sum(alive)) &
                               jnp.isfinite(gathered), gathered, 0.0)
        return out_boxes, out_scores

    def _compact_indices(alive, pre, post):
        """Indices of the first `post` survivors (first index repeated as
        padding when fewer survive)."""
        key = jnp.where(alive, jnp.arange(pre), pre)
        order = jnp.argsort(key)          # survivors first, in order
        first = order[0]
        idx = order[:post] if pre >= post else jnp.concatenate(
            [order, jnp.full((post - pre,), first, jnp.int32)])
        n_alive = jnp.sum(alive)
        return jnp.where(jnp.arange(post) < n_alive.clip(1), idx, first)

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=boxes.dtype), post)
    rois = jnp.concatenate([batch_idx[:, None],
                            boxes.reshape(N * post, 4)], axis=-1)
    if parse_bool(attrs.get("output_score", False)):
        return rois, scores.reshape(N * post, 1)
    return rois

alias("_contrib_MultiProposal", "MultiProposal")
alias("_contrib_Proposal", "MultiProposal")
alias("Proposal", "MultiProposal")
