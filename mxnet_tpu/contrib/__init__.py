"""contrib namespace (reference: python/mxnet/contrib/ + contrib ops)."""
from . import ops  # noqa: F401 — registers contrib ops
from .. import autograd  # mx.contrib.autograd compat alias
