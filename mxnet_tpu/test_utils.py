"""Test fixtures (reference: python/mxnet/test_utils.py).

The reference's check_* helpers make every op test cheap (SURVEY.md §4):
``check_numeric_gradient`` (finite differences vs symbolic backward,
test_utils.py:360), ``check_symbolic_forward/backward`` (:473, :526),
``assert_almost_equal`` (:128), ``check_consistency`` (:676 — the CPU<->GPU
parity harness, here CPU-jax vs TPU).
"""
from __future__ import annotations

import numpy as np

from .context import Context, cpu, current_context
from .ndarray import NDArray, array, zeros
from . import ndarray as nd
from .symbol import Symbol
from . import random as _random


def default_context():
    return current_context()


def set_default_context(ctx):
    Context._local.stack = [ctx]


def default_dtype():
    return np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, ctx=None, dtype=np.float32):
    return array(np.random.uniform(-1, 1, shape).astype(dtype), ctx=ctx)


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """reference: test_utils.py np_reduce."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else \
            range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def _parse_location(sym, location, ctx):
    """reference: test_utils.py _parse_location."""
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(sym.list_arguments()):
            raise ValueError(
                f"Symbol arguments and keys of the given location do not "
                f"match. symbol args:{sym.list_arguments()}, "
                f"location.keys():{list(location.keys())}")
    else:
        location = {k: v for k, v in zip(sym.list_arguments(), location)}
    location = {k: array(v, ctx=ctx) if isinstance(v, np.ndarray)
                else v for k, v in location.items()}
    return location


def _parse_aux_states(sym, aux_states, ctx):
    if aux_states is not None:
        if isinstance(aux_states, dict):
            if set(aux_states.keys()) != set(sym.list_auxiliary_states()):
                raise ValueError("Symbol aux_states names and given "
                                 "aux_states do not match.")
        elif isinstance(aux_states, (list, tuple)):
            aux_names = sym.list_auxiliary_states()
            aux_states = {k: v for k, v in zip(aux_names, aux_states)}
        aux_states = {k: array(v, ctx=ctx) if isinstance(v, np.ndarray)
                      else v for k, v in aux_states.items()}
    return aux_states


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """reference: test_utils.py:128."""
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    if atol is None:
        atol = 1e-20
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} vs {names[1]}")


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    return np.allclose(a, b, rtol=rtol, atol=atol)


def same(a, b):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    return np.array_equal(a, b)


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None):
    """Finite differences vs symbolic backward. reference:
    test_utils.py:360."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    location_npy = {k: v.asnumpy() for k, v in location.items()}
    aux_states = _parse_aux_states(sym, aux_states, ctx)
    if aux_states is not None:
        aux_states_npy = {k: v.asnumpy() for k, v in aux_states.items()}
    else:
        aux_states_npy = None
    if grad_nodes is None:
        grad_nodes = sym.list_arguments()
        grad_req = {k: "write" for k in grad_nodes}
    elif isinstance(grad_nodes, (list, tuple)):
        grad_nodes = list(grad_nodes)
        grad_req = {k: "write" if k in grad_nodes else "null"
                    for k in sym.list_arguments()}
    elif isinstance(grad_nodes, dict):
        grad_req = grad_nodes.copy()
        grad_nodes = list(grad_nodes.keys())
    else:
        raise ValueError

    input_shape = {k: v.shape for k, v in location.items()}
    _, out_shape, _ = sym.infer_shape(**input_shape)
    proj = Symbol.__new__(Symbol)  # random projection to scalar loss
    from . import symbol as _sym
    out = _sym.MakeLoss(_sym.sum(sym * _sym.var("__random_proj")))
    location = dict(location)
    proj_arr = np.random.uniform(-1.0, 1.0, size=out_shape[0])
    location["__random_proj"] = array(proj_arr, ctx=ctx)
    args_grad = {k: zeros(location[k].shape, ctx=ctx)
                 for k in grad_nodes + ["__random_proj"]}
    grad_req = dict(grad_req)
    grad_req["__random_proj"] = "write"

    executor = out.bind(ctx, args=location, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)
    executor.forward(is_train=True)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy()
                      for k in grad_nodes}

    # numeric gradient by central differences on the projected scalar;
    # ONE executor bound outside the loop so the jitted program is reused
    # for every perturbation (compile once, run 2*size times)
    eval_args = {k: array(v, ctx=ctx) for k, v in location_npy.items()}
    eval_args["__random_proj"] = array(proj_arr, ctx=ctx)
    ex2 = out.bind(ctx, args=eval_args, grad_req="null",
                   aux_states=_parse_aux_states(sym, aux_states_npy, ctx)
                   if aux_states_npy else None)

    def eval_loss(loc_npy):
        for k, v in loc_npy.items():
            eval_args[k]._set(__import__("jax").numpy.asarray(
                v.astype(np.float32)))
        ex2.forward(is_train=use_forward_train)
        return float(np.sum(ex2.outputs[0].asnumpy()))

    for name in grad_nodes:
        base = {k: v.copy() for k, v in location_npy.items()}
        grad_np = np.zeros(base[name].shape, dtype=np.float64)
        flat = base[name].reshape(-1)
        gflat = grad_np.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps / 2
            fp = eval_loss(base)
            flat[i] = orig - numeric_eps / 2
            fm = eval_loss(base)
            flat[i] = orig
            gflat[i] = (fp - fm) / numeric_eps
        assert_almost_equal(grad_np, symbolic_grads[name], rtol=rtol,
                            atol=atol if atol is not None else rtol * 1e-1,
                            names=(f"numeric-{name}", f"symbolic-{name}"))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None):
    """reference: test_utils.py:473."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    aux_states = _parse_aux_states(sym, aux_states, ctx)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    args_grad_data = {k: zeros(v.shape, ctx=ctx)
                      for k, v in location.items()}
    executor = sym.bind(ctx, args=location, args_grad=args_grad_data,
                        aux_states=aux_states)
    executor.forward(is_train=False)
    outputs = [x.asnumpy() for x in executor.outputs]
    for output_name, expect, output in zip(sym.list_outputs(), expected,
                                           outputs):
        assert_almost_equal(expect, output, rtol, atol,
                            (f"EXPECTED_{output_name}", output_name))
    return executor.outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """reference: test_utils.py:526."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    aux_states = _parse_aux_states(sym, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym.list_arguments(), expected)}
    args_grad_npy = {k: np.random.normal(size=v.shape)
                     for k, v in expected.items()}
    args_grad_data = {k: array(v, ctx=ctx)
                      for k, v in args_grad_npy.items()}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in sym.list_arguments()}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = {k: v for k, v in zip(sym.list_arguments(), grad_req)}
    executor = sym.bind(ctx, args=location, args_grad=args_grad_data,
                        aux_states=aux_states, grad_req=grad_req)
    executor.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [array(v, ctx=ctx) if isinstance(v, np.ndarray) else v
                     for v in out_grads]
    elif isinstance(out_grads, (dict)):
        out_grads = [array(out_grads[k], ctx=ctx)
                     for k in sym.list_outputs()]
    elif out_grads is None:
        pass
    else:
        raise ValueError("out_grads must be dict, list or None")
    executor.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in executor.grad_dict.items()
             if v is not None}
    for name in expected:
        if grad_req[name] == "write":
            assert_almost_equal(expected[name], grads[name], rtol, atol,
                                (f"EXPECTED_{name}", name))
        elif grad_req[name] == "add":
            assert_almost_equal(expected[name],
                                grads[name] - args_grad_npy[name],
                                rtol, atol, (f"EXPECTED_{name}", name))
        elif grad_req[name] == "null":
            assert_almost_equal(args_grad_npy[name], grads[name],
                                rtol, atol, (f"EXPECTED_{name}", name))
    return executor.grad_dict


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True):
    """Cross-device parity harness. reference: test_utils.py:676 — run the
    same symbol under every (ctx, dtype) config and compare fwd/bwd
    pairwise against the most precise one."""
    if tol is None:
        tol = {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
               np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
               np.dtype(np.int32): 0}
    elif isinstance(tol, float):
        tol = {np.dtype(np.float16): tol, np.dtype(np.float32): tol,
               np.dtype(np.float64): tol, np.dtype(np.uint8): tol,
               np.dtype(np.int32): tol}
    assert len(ctx_list) > 1
    if isinstance(sym, Symbol):
        sym = [sym] * len(ctx_list)
    else:
        assert len(sym) == len(ctx_list)
    output_names = sym[0].list_outputs()
    arg_names = sym[0].list_arguments()
    exe_list = []
    for s, ctx in zip(sym, ctx_list):
        assert s.list_arguments() == arg_names
        assert s.list_outputs() == output_names
        exe_list.append(s.simple_bind(grad_req=grad_req, **ctx))
    arg_dict = {}
    for n, arr in exe_list[0].arg_dict.items():
        arg_dict[n] = np.random.normal(size=arr.shape, scale=scale)
        if arg_params is not None and n in arg_params:
            arg_dict[n] = arg_params[n]
    aux_dict = {}
    for n, arr in exe_list[0].aux_dict.items():
        aux_dict[n] = np.random.normal(size=arr.shape, scale=scale)
        if aux_params is not None and n in aux_params:
            aux_dict[n] = aux_params[n]
    for exe in exe_list:
        for name, arr in exe.arg_dict.items():
            arr[:] = arg_dict[name]
        for name, arr in exe.aux_dict.items():
            arr[:] = aux_dict[name]
    def _exe_dtype(exe):
        """Least-precise float among the executor's inputs and outputs —
        some ops upcast internally (e.g. f16 in, f32 out), and the bound
        precision, not the output dtype, is what tolerance must track."""
        cands = [np.dtype(a.dtype) for a in exe.arg_dict.values()]
        cands += [np.dtype(o.dtype) for o in exe.outputs]
        floats = [d for d in cands if d.kind == "f"]
        if not floats:
            return np.dtype(exe.outputs[0].dtype) if exe.outputs \
                else np.dtype(np.float32)
        return min(floats, key=lambda d: d.itemsize)

    dtypes = [_exe_dtype(exe) for exe in exe_list]
    # forward
    for exe in exe_list:
        exe.forward(is_train=False)
    max_idx = int(np.argmax([d.itemsize for d in dtypes]))
    gt = [o.asnumpy() for o in exe_list[max_idx].outputs]
    for i, exe in enumerate(exe_list):
        if i == max_idx:
            continue
        rtol = tol[dtypes[i]]
        for name, arr, garr in zip(output_names, exe.outputs, gt):
            assert_almost_equal(arr.asnumpy().astype(dtypes[max_idx]), garr,
                                rtol=rtol, atol=rtol,
                                names=(f"exe{i}-{name}",
                                       f"exe{max_idx}-{name}"))
    # train + backward
    if grad_req != "null":
        for exe in exe_list:
            exe.forward(is_train=True)
            exe.backward([NDArray(o.asjax()) for o in exe.outputs])
        gt_g = {n: g.asnumpy() for n, g in
                exe_list[max_idx].grad_dict.items() if g is not None}
        for i, exe in enumerate(exe_list):
            if i == max_idx:
                continue
            rtol = tol[dtypes[i]]
            for name, arr in exe.grad_dict.items():
                if arr is None:
                    continue
                assert_almost_equal(
                    arr.asnumpy().astype(dtypes[max_idx]), gt_g[name],
                    rtol=rtol, atol=rtol,
                    names=(f"grad-exe{i}-{name}", f"grad-exe{max_idx}-{name}"))
    return gt


def check_speed(sym, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Timing helper. reference: test_utils.py:602."""
    import time
    ctx = ctx or default_context()
    if grad_req is None:
        grad_req = "write"
    if location is None:
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx, **kwargs)
        location = {k: np.random.normal(size=arr.shape, scale=1.0)
                    for k, arr in exe.arg_dict.items()}
    else:
        assert isinstance(location, dict)
        exe = sym.simple_bind(grad_req=grad_req, ctx=ctx,
                              **{k: v.shape for k, v in location.items()})
    for name, iarr in location.items():
        exe.arg_dict[name][:] = iarr.astype(exe.arg_dict[name].dtype)

    if typ == "whole":
        exe.forward(is_train=True)
        exe.backward()
        for output in exe.outputs:
            output.wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=True)
            exe.backward()
            for output in exe.outputs:
                output.wait_to_read()
        toc = time.time()
        return (toc - tic) / N
    if typ == "forward":
        exe.forward(is_train=False)
        for output in exe.outputs:
            output.wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=False)
            for output in exe.outputs:
                output.wait_to_read()
        toc = time.time()
        return (toc - tic) / N
    raise ValueError("typ can only be whole or forward")


def check_cache_key_knob(builder, flip, restore=None, name="knob"):
    """Runtime half of the CK3xx cache-key completeness contract
    (analysis/cachekey.py): assert that one registered shape-affecting
    knob actually lands in the program-cache key.

    ``builder()`` runs a program-building workload (bind + step).  The
    check replays it unflipped and requires ZERO new compiles (the key
    is not over-wide), then applies ``flip()`` (set the env var, swap
    the symbol, change the dtype) and requires at least one new compile
    (the key is not under-wide — a flipped knob must not silently reuse
    a stale program, the PR-11/PR-17 bug class).  ``restore()`` undoes
    the flip; it runs even when the assertion fails."""
    from . import program_cache as _progcache

    builder()
    c0 = _progcache.compile_count()
    builder()
    c_replay = _progcache.compile_count()
    assert c_replay == c0, (
        f"cache-key check for {name!r}: unflipped replay recompiled "
        f"({c_replay - c0} new compile(s)) — the key carries something "
        "that changes between identical runs")
    try:
        flip()
        builder()
        c_flip = _progcache.compile_count()
        assert c_flip > c0, (
            f"cache-key check for {name!r}: flipping the knob added "
            "zero compiles — the program cache replayed a stale "
            "program traced under the other setting (knob missing "
            "from the key)")
    finally:
        if restore is not None:
            restore()
