"""Symbol: declarative graph construction.

The reference Symbol is a handle into the NNVM C++ graph IR
(reference: python/mxnet/symbol.py:1-1756, nnvm submodule) — composition by
``__call__``, bidirectional shape/type inference passes, JSON save/load,
``simple_bind``/``bind`` into a GraphExecutor.

TPU-native design: the graph IR lives in Python (Node/Symbol below) because
its ONLY job is to produce a traced JAX function — XLA is the real graph
compiler (memory planning, fusion, scheduling = PlanMemory/bulk-exec/engine
of the reference). The IR therefore stays minimal: nodes with typed attrs,
topological evaluation, and an MXNet-style JSON wire format for checkpoint
parity. Gradient construction is NOT a graph pass: ``bind`` hands the traced
function to ``jax.vjp`` (see executor.py).
"""
from __future__ import annotations

import json

import numpy as np

from .base import (MXNetError, attr_to_str, str_to_attr, merge_shape,
                   shape_is_known)

_merge_shape = merge_shape
from .context import current_context
from .ops.registry import OP_REGISTRY, get_op
from . import attribute, name as _name_mod

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange"]


class Node:
    """One op instance (or variable) in the graph."""

    __slots__ = ("op", "name", "attrs", "inputs", "_extra")

    def __init__(self, op, name, attrs=None, inputs=None, extra=None):
        self.op = op                  # op name, or None for variables
        self.name = name
        self.attrs = attrs or {}      # typed op params
        self.inputs = inputs or []    # list of (Node, out_index)
        self._extra = extra or {}     # user attrs (__lr_mult__, ctx_group...)

    @property
    def is_variable(self):
        return self.op is None

    def opdef(self):
        return get_op(self.op)


class Symbol:
    """A set of output entries over the node graph."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # [(Node, int)]

    # ------------------------------------------------------------- graph walk
    def _topo_nodes(self):
        seen, order = set(), []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._outputs:
            visit(node)
        return order

    def _arg_nodes(self):
        return [n for n in self._topo_nodes()
                if n.is_variable and not n._extra.get("__is_aux__")]

    def _aux_nodes(self):
        return [n for n in self._topo_nodes()
                if n.is_variable and n._extra.get("__is_aux__")]

    # -------------------------------------------------------------- listings
    def list_arguments(self):
        return [n.name for n in self._arg_nodes()]

    def list_auxiliary_states(self):
        return [n.name for n in self._aux_nodes()]

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
                continue
            onames = node.opdef().output_names(node.attrs)
            names.append(f"{node.name}_{onames[idx]}")
        return names

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    # ------------------------------------------------------------ attributes
    def attr(self, key):
        node = self._outputs[0][0]
        val = node._extra.get(key)
        if val is None and key in node.attrs:
            return attr_to_str(node.attrs[key])
        return val

    def attr_dict(self):
        ret = {}
        for node in self._topo_nodes():
            d = {k: attr_to_str(v) for k, v in node.attrs.items()}
            d.update({k: v for k, v in node._extra.items()
                      if not k.startswith("__is_aux__")})
            if d:
                ret[node.name] = d
        return ret

    def _set_attr(self, **kwargs):
        for k, v in kwargs.items():
            self._outputs[0][0]._extra[k] = v

    # ------------------------------------------------------------ composition
    def __call__(self, *args, **kwargs):
        """Compose: substitute this symbol's free variables.

        reference: symbol.py __call__/_compose — positional args match
        list_arguments order, kwargs match variable names. Returns a new
        Symbol with the substitution applied (graphs are immutable).
        """
        arg_names = self.list_arguments()
        mapping = {}
        if args:
            for nm, a in zip(arg_names, args):
                mapping[nm] = a
        for k, v in kwargs.items():
            if k == "name":
                continue
            mapping[k] = v
        for k, v in mapping.items():
            if not isinstance(v, Symbol):
                raise TypeError("compose expects Symbol arguments")
        return self._substitute(mapping)

    def _substitute(self, mapping):
        memo = {}

        def clone(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.is_variable and node.name in mapping:
                sub = mapping[node.name]
                result = sub._outputs[0]
                memo[id(node)] = result
                return result
            new = Node(node.op, node.name, dict(node.attrs), [],
                       dict(node._extra))
            memo[id(node)] = (new, None)
            new.inputs = [(clone(inp)[0], idx if clone(inp)[1] is None
                           else clone(inp)[1])
                          for inp, idx in node.inputs]
            # fix: for substituted inputs the entry index comes from mapping
            fixed = []
            for (inp, idx) in node.inputs:
                cn, ci = clone(inp)
                fixed.append((cn, idx if ci is None else ci))
            new.inputs = fixed
            return (new, None)

        outs = []
        for node, idx in self._outputs:
            cn, ci = clone(node)
            outs.append((cn, idx if ci is None else ci))
        return Symbol(outs)

    # ------------------------------------------------------------- accessors
    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise ValueError(f"no output named {index!r}")
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def get_internals(self):
        """Symbol over every node output. reference: symbol.py internals."""
        outs = []
        for node in self._topo_nodes():
            if node.is_variable:
                outs.append((node, 0))
            else:
                for i in range(node.opdef().num_outputs(node.attrs)):
                    outs.append((node, i))
        return Symbol(outs)

    def get_output(self, index):
        return self[index]

    # ------------------------------------------------------------ arithmetic
    def _binary_op(self, other, opname, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            return _create(opname, [self, other])
        if isinstance(other, (int, float, np.generic)):
            return _create(scalar_op, [self], scalar=float(other))
        return NotImplemented

    def __add__(self, o): return self._binary_op(o, "_plus", "_add_scalar")
    __radd__ = __add__
    def __sub__(self, o): return self._binary_op(o, "_minus", "_sub_scalar")

    def __rsub__(self, o):
        return _create("_rsub_scalar", [self], scalar=float(o))

    def __mul__(self, o): return self._binary_op(o, "_mul", "_mul_scalar")
    __rmul__ = __mul__
    def __truediv__(self, o): return self._binary_op(o, "_div", "_div_scalar")
    __div__ = __truediv__

    def __rtruediv__(self, o):
        return _create("_rdiv_scalar", [self], scalar=float(o))
    __rdiv__ = __rtruediv__

    def __pow__(self, o): return self._binary_op(o, "_power", "_power_scalar")
    def __neg__(self): return _create("negative", [self])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __repr__(self):
        name = self.name
        return f"<Symbol {name if name else 'Grouped'}>"

    # -------------------------------------------------------------- inference
    def infer_shape(self, *args, **kwargs):
        """Bidirectional shape inference over the graph.

        Forward-propagates known shapes node by node using each op's
        infer_shape (which also fills weight/bias shapes — the reference's
        InferShape pass, graph_executor.cc:425). Returns (arg_shapes,
        out_shapes, aux_shapes) in listing order; None entries when unknown.
        """
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        """Fixpoint shape inference with partial shapes.

        A shape may contain 0 for an unknown dim (the reference's
        convention, e.g. RNN begin_state declared (0, H)). Passes run
        repeatedly, merging information forward and backward through
        per-op infer functions, until nothing changes — the analog of
        NNVM's iterative InferShape pass.
        """
        arg_names = self.list_arguments()
        known = {}
        if args:
            for nm, s in zip(arg_names, args):
                if s is not None:
                    known[nm] = tuple(s)
        for k, v in kwargs.items():
            known[k] = tuple(v)
        shapes = self._infer_entry_shapes(known)

        def _final(s):
            if s is None or 0 in s:
                return None if not partial else s
            return s

        arg_shapes = [_final(shapes[id(n)][0]) for n in self._arg_nodes()]
        aux_shapes = [_final(shapes[id(n)][0]) for n in self._aux_nodes()]
        out_shapes = [_final(shapes[id(n)][i]) for n, i in self._outputs]
        if not partial and any(s is None for s in arg_shapes):
            missing = [nm for nm, s in zip(arg_names, arg_shapes)
                       if s is None]
            raise MXNetError(f"cannot infer shapes for arguments {missing}; "
                             "provide more input shapes")
        return arg_shapes, out_shapes, aux_shapes

    def _infer_entry_shapes(self, known):
        """Fixpoint pass core: returns {id(node): [partial out shapes]}."""
        nodes = self._topo_nodes()
        shapes = {}  # id(node) -> list of partial shapes (None | tuple)
        for node in nodes:
            if node.is_variable:
                seed = known.get(node.name)
                if seed is None and "__shape__" in node._extra:
                    hint = str_to_attr(node._extra["__shape__"])
                    if isinstance(hint, (tuple, list)):
                        seed = tuple(int(d) for d in hint)
                shapes[id(node)] = [seed]
            else:
                n_out = node.opdef().num_outputs(node.attrs)
                shapes[id(node)] = [None] * n_out

        for _ in range(4):  # fixpoint iterations
            changed = False
            for node in nodes:
                if node.is_variable:
                    continue
                opdef = node.opdef()
                in_entries = [(shapes[id(inp)], idx)
                              for inp, idx in node.inputs]
                in_shapes = [store[idx] for store, idx in in_entries]
                new_in, out_shapes, _aux = _infer_node_shape(
                    opdef, node, in_shapes, True,
                    out_known=list(shapes[id(node)]))
                try:
                    for (store, idx), s in zip(in_entries, new_in):
                        merged = _merge_shape(store[idx], s)
                        if merged != store[idx]:
                            store[idx] = merged
                            changed = True
                    store = shapes[id(node)]
                    for i, s in enumerate(out_shapes[:len(store)]):
                        merged = _merge_shape(store[i], s)
                        if merged != store[i]:
                            store[i] = merged
                            changed = True
                except MXNetError as e:
                    # conflicting shapes meeting at this node: attach
                    # the node's provenance instead of the bare
                    # "incompatible shapes (a) vs (b)"
                    raise MXNetError(
                        f"infer_shape mismatch at "
                        f"{_node_provenance(node, in_shapes)}: {e}") \
                        from e
            if not changed:
                break
        return shapes

    def infer_type(self, *args, **kwargs):
        """Type inference: defaults to float32 propagation."""
        arg_names = self.list_arguments()
        known = {}
        if args:
            for nm, t in zip(arg_names, args):
                if t is not None:
                    known[nm] = np.dtype(t)
        for k, v in kwargs.items():
            known[k] = np.dtype(v)
        default = next(iter(known.values())) if known else np.dtype("float32")
        arg_types = [known.get(nm, default) for nm in arg_names]
        out_types = [default] * len(self._outputs)
        aux_types = [np.dtype("float32")] * len(self._aux_nodes())
        return arg_types, out_types, aux_types

    # ----------------------------------------------------------- serialization
    def tojson(self):
        """MXNet-style JSON graph (reference: nnvm SaveJSON,
        c_api_symbolic.cc:330-361): nodes + arg_nodes + heads."""
        nodes = self._topo_nodes()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jn = {
                "op": "null" if n.is_variable else n.op,
                "name": n.name,
                "inputs": [[node_ids[id(inp)], idx, 0]
                           for inp, idx in n.inputs],
            }
            attrs = {k: attr_to_str(v) for k, v in n.attrs.items()}
            attrs.update({k: str(v) for k, v in n._extra.items()})
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
        arg_nodes = [node_ids[id(n)] for n in nodes if n.is_variable]
        heads = [[node_ids[id(n)], i, 0] for n, i in self._outputs]
        return json.dumps({"nodes": jnodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": [], "heads": heads,
                           "attrs": {"mxnet_version": ["int", 905]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ----------------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, mirror=None, validate=None, **kwargs):
        from .executor import Executor
        return Executor._simple_bind(self, ctx or current_context(), grad_req,
                                     type_dict, group2ctx, kwargs,
                                     mirror=mirror, validate=validate)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None, mirror=None,
             validate=None):
        """Bind into an Executor. ``validate="warn"|"raise"`` runs the
        static-analysis passes (mxnet_tpu.analysis) over the bound
        graph — warn logs findings, raise fails the bind on
        error-severity ones; default comes from MXNET_GRAPH_VALIDATE."""
        from .executor import Executor
        return Executor(self, ctx or current_context(), args, args_grad,
                        grad_req, aux_states, group2ctx, shared_exec,
                        mirror=mirror, validate=validate)

    # ------------------------------------------------------------ eval helper
    def eval(self, ctx=None, **kwargs):
        shapes = {k: v.shape for k, v in kwargs.items()}
        ex = self.simple_bind(ctx=ctx or current_context(), grad_req="null",
                              **shapes)
        return ex.forward(is_train=False, **kwargs)


def _node_provenance(node, in_shapes=None):
    """'op X node Y (inputs: a=(2, 3), b=?)' — the provenance prefix
    every inference error carries (reference: InferShape errors named
    the failing node; a bare "incompatible shapes" is undebuggable on a
    500-node graph)."""
    parts = []
    for i, (inp, idx) in enumerate(node.inputs):
        nm = inp.name if inp.is_variable else f"{inp.name}[{idx}]"
        s = None
        if in_shapes is not None and i < len(in_shapes):
            s = in_shapes[i]
        parts.append(f"{nm}={s if s is not None else '?'}")
    inputs = f" (inputs: {', '.join(parts)})" if parts else ""
    return f"op {node.op!r} node {node.name!r}{inputs}"


def _infer_node_shape(opdef, node, in_shapes, partial, out_known=None):
    aux_count = len(opdef.aux_names(node.attrs))
    regular = in_shapes[:len(in_shapes) - aux_count] if aux_count else in_shapes
    if opdef.infer_shape is not None:
        # arity is validated (and the out_known capability probed) at
        # registration time (ops/registry.py); the getattr fallback
        # keeps hand-built OpDef objects working
        accepts_out = getattr(opdef, "_infer_accepts_out", False)
        try:
            if accepts_out:
                new_in, outs, auxs = opdef.infer_shape(
                    node.attrs, regular, out_known)
            else:
                new_in, outs, auxs = opdef.infer_shape(node.attrs, regular)
        except (KeyError, IndexError, TypeError) as e:
            # incomplete information inside the infer fn: unknown in a
            # partial walk, a provenance-carrying error otherwise
            if partial:
                n_out = opdef.num_outputs(node.attrs)
                return in_shapes, [None] * n_out, []
            raise MXNetError(
                f"infer_shape failed at "
                f"{_node_provenance(node, in_shapes)}: {e}") from e
        except (ValueError, MXNetError) as e:
            # genuine inconsistency (shape conflict, bad attr): always
            # surface, with the node's provenance attached
            raise MXNetError(
                f"infer_shape failed at "
                f"{_node_provenance(node, in_shapes)}: {e}") from e
        return list(new_in) + list(auxs), outs, auxs
    if opdef.shape_passthrough:
        # declared shape-identity on input 0 (the explicit flag the
        # graph verifier accepts in place of infer_shape): propagate
        # bidirectionally between input 0 and every output
        try:
            merged = regular[0] if regular else None
            for s in (out_known or []):
                merged = _merge_shape(merged, s)
        except MXNetError as e:
            raise MXNetError(
                f"infer_shape failed at "
                f"{_node_provenance(node, in_shapes)}: {e}") from e
        n_out = opdef.num_outputs(node.attrs)
        new_in = [merged] + list(in_shapes[1:])
        return new_in, [merged] * n_out, []
    # fallback: abstract evaluation requires complete input shapes
    if any(not shape_is_known(s) for s in in_shapes):
        n_out = opdef.num_outputs(node.attrs)
        return in_shapes, [None] * n_out, []
    import jax
    import jax.numpy as jnp

    def run(*arrs):
        reg = list(arrs[:len(arrs) - aux_count]) if aux_count else list(arrs)
        aux = list(arrs[len(arrs) - aux_count:]) if aux_count else []
        outs, _ = opdef.forward(node.attrs, reg, aux, False,
                                jax.random.PRNGKey(0) if opdef.need_rng
                                else None)
        return outs

    dummies = [jax.ShapeDtypeStruct(tuple(s), np.float32) for s in in_shapes]
    try:
        out_shapes = [tuple(o.shape) for o in jax.eval_shape(run, *dummies)]
    except Exception as e:  # noqa: BLE001 — surface as inference failure
        if partial:
            n_out = opdef.num_outputs(node.attrs)
            return in_shapes, [None] * n_out, []
        raise MXNetError(
            f"shape inference (abstract evaluation) failed at "
            f"{_node_provenance(node, in_shapes)}: {e}")
    aux_shapes = out_shapes[len(out_shapes):]
    return in_shapes, out_shapes, aux_shapes


# ------------------------------------------------------------------ factories
def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, **kwargs):
    """Create a variable symbol. reference: symbol.py Variable."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    extra = attribute.current_attrs(attr)
    extra = dict(extra) if extra else {}
    if lr_mult is not None:
        extra["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        extra["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        extra["__dtype__"] = str(np.dtype(dtype))
    if shape is not None:
        extra["__shape__"] = str(tuple(shape))
    if init is not None:
        extra["__init__"] = init if isinstance(init, str) else \
            init.dumps() if hasattr(init, "dumps") else str(init)
    extra.update({k: str(v) for k, v in kwargs.items()})
    return Symbol([(Node(None, name, extra=extra), 0)])


Variable = var


def Group(symbols):
    """Group symbols into one multi-output symbol. reference: sym.Group."""
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load_json(json_str):
    data = json.loads(json_str)
    jnodes = data["nodes"]
    built = []
    for jn in jnodes:
        # merge the legacy key spellings of pre-NNVM checkpoints: op
        # params lived in "param" and user attributes in "attr" on the
        # SAME node (reference: legacy_json_util.cc:178 UpgradeJSON)
        attrs_raw = {}
        for key in ("param", "attr", "attrs"):
            attrs_raw.update(jn.get(key) or {})
        op = jn["op"]
        if op == "null":
            node = Node(None, jn["name"],
                        extra={k: v for k, v in attrs_raw.items()})
            if attrs_raw.get("__is_aux__") == "True":
                node._extra["__is_aux__"] = True
        else:
            opdef = get_op(op)
            # reserved user attributes ride in _extra, not op attrs —
            # ctx_group placement tags must survive a JSON round-trip
            # (tojson serializes _extra into the same dict)
            reserved = {"ctx_group", "lr_mult", "wd_mult"}
            attrs = opdef.normalize_attrs(
                {k: str_to_attr(v) for k, v in attrs_raw.items()
                 if not k.startswith("__") and k not in reserved})
            extra = {k: v for k, v in attrs_raw.items()
                     if k.startswith("__") or k in reserved}
            node = Node(op, jn["name"], attrs, extra=extra)
        node.inputs = [(built[i], oi) for i, oi, *_ in jn["inputs"]]
        built.append(node)
    heads = data.get("heads", [[len(built) - 1, 0, 0]])
    # restore aux marking from op aux slots
    for node in built:
        if node.is_variable or node.op is None:
            continue
        opdef = get_op(node.op)
        aux_n = len(opdef.aux_names(node.attrs))
        if aux_n:
            for inp, _ in node.inputs[len(node.inputs) - aux_n:]:
                if inp.is_variable:
                    inp._extra["__is_aux__"] = True
    return Symbol([(built[i], oi) for i, oi, *_ in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------- op creation
def _create(op_name, input_syms, name=None, attr=None, **params):
    """Build a Symbol node for a registered op (the symbolic invoke path)."""
    opdef = get_op(op_name)
    attrs = opdef.normalize_attrs(params)
    node_name = _name_mod.current().get(name, op_name.strip("_"))
    extra = attribute.current_attrs(attr)
    extra = dict(extra) if extra else {}

    in_names = opdef.input_names(attrs)
    aux_names = opdef.aux_names(attrs)
    inputs = []
    for i, inm in enumerate(in_names):
        if i < len(input_syms) and input_syms[i] is not None:
            s = input_syms[i]
            if len(s._outputs) != 1:
                raise MXNetError(
                    f"op {op_name} input {inm} must be single-output")
            inputs.append(s._outputs[0])
        else:
            # auto-create missing weight/bias variables (reference: compose
            # auto-creates named vars per ListArguments)
            vnode = Node(None, f"{node_name}_{inm}", extra=dict(extra))
            inputs.append((vnode, 0))
    for anm in aux_names:
        aux_extra = {**extra, "__is_aux__": True}
        # an op may declare a non-f32 aux cell (attention_decode's int32
        # cache cursor): stamp it onto the auto-created variable so
        # binding honors it (and the mixed-precision cast exempts it)
        adt = opdef.aux_dtypes.get(anm)
        if callable(adt):
            # attr-dependent cells (attention_decode's fp8 KV storage):
            # the callable sees the node attrs and returns None for the
            # default compute-width cell (no stamp — unchanged graphs
            # serialize byte-identically)
            adt = adt(attrs or {})
        if adt is not None:
            aux_extra["__dtype__"] = str(np.dtype(adt))
        vnode = Node(None, f"{node_name}_{anm}", extra=aux_extra)
        inputs.append((vnode, 0))

    node = Node(op_name, node_name, attrs, inputs, extra)
    n_out = opdef.num_visible_outputs(attrs)
    return Symbol([(node, i) for i in range(n_out)])


def _make_symbol_function(op_name):
    opdef = get_op(op_name)

    def creator(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        opdef_local = opdef
        in_names = opdef_local.input_names(
            opdef_local.normalize_attrs(
                {k: v for k, v in kwargs.items()
                 if not isinstance(v, Symbol)}))
        input_syms = list(args)
        # keyword inputs (data=..., weight=...)
        sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        params = {k: v for k, v in kwargs.items()
                  if not isinstance(v, Symbol)}
        if sym_kwargs:
            by_name = [None] * len(in_names)
            for i, s in enumerate(input_syms):
                by_name[i] = s
            for k, v in sym_kwargs.items():
                if k in in_names:
                    by_name[in_names.index(k)] = v
                else:
                    # variadic ops (Concat) accept arbitrary kw names
                    try:
                        slot = by_name.index(None)
                        by_name[slot] = v
                    except ValueError:
                        by_name.append(v)
            input_syms = by_name
        # variadic ops: positional args beyond spec extend num_args
        if opdef_local._inputs and callable(opdef_local._inputs):
            if "num_args" in opdef_local.attr_spec and \
                    "num_args" not in params:
                params["num_args"] = len([s for s in input_syms
                                          if s is not None]) or len(args)
        return _create(op_name, input_syms, name=name, attr=attr, **params)

    creator.__name__ = op_name
    creator.__doc__ = opdef.doc or f"symbolic {op_name}"
    return creator


def _init_symbol_module(module_dict):
    """Auto-generate mx.sym.<op> functions (reference: symbol.py:1585)."""
    for op_name in list(OP_REGISTRY):
        if op_name.startswith("_backward"):
            continue
        fn = _make_symbol_function(op_name)
        module_dict[op_name] = fn
        if op_name.startswith("_") and op_name[1:] not in module_dict:
            pass


def zeros(shape, dtype=None, name=None):
    return _create("_zeros", [], name=name, shape=shape,
                   dtype=str(np.dtype(dtype or "float32")))


def ones(shape, dtype=None, name=None):
    return _create("_ones", [], name=name, shape=shape,
                   dtype=str(np.dtype(dtype or "float32")))


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, name=None):
    return _create("_arange", [], name=name, start=start, stop=stop,
                   step=step, repeat=repeat,
                   dtype=str(np.dtype(dtype or "float32")))


# ------------------------------------------------- scalar/symbol helpers
def _sym_scalar_dispatch(both, lscalar, rscalar, pyfn, name):
    """reference: symbol.py pow/maximum/minimum/hypot — dispatch on
    Symbol-vs-Number operand combinations over the injected ops."""
    def fn(left, right):
        g = globals()
        if isinstance(left, Symbol) and isinstance(right, Symbol):
            return g[both](left, right)
        if isinstance(left, Symbol):
            return g[lscalar](left, scalar=float(right))
        if isinstance(right, Symbol):
            return g[rscalar](right, scalar=float(left))
        return pyfn(left, right)
    fn.__name__ = name
    fn.__doc__ = (f"``{name}(left, right)`` over Symbol/Number operands "
                  "(reference: symbol.py module helpers).")
    return fn


pow = _sym_scalar_dispatch("_power", "_power_scalar", "_rpower_scalar",
                           lambda a, b: a ** b, "pow")
maximum = _sym_scalar_dispatch("_maximum", "_maximum_scalar",
                               "_maximum_scalar",
                               lambda a, b: a if a > b else b, "maximum")
minimum = _sym_scalar_dispatch("_minimum", "_minimum_scalar",
                               "_minimum_scalar",
                               lambda a, b: a if a < b else b, "minimum")
hypot = _sym_scalar_dispatch("_hypot", "_hypot_scalar", "_hypot_scalar",
                             lambda a, b: float(np.hypot(a, b)), "hypot")
