"""Execution-time NHWC layout propagation (a graph-level layout pass).

The reference framework is NCHW end to end — mshadow's and cuDNN's
native layout (reference: src/operator/convolution-inl.h:1-570). On TPU
the MXU/VPU want the channel dimension minor (in lanes): NHWC. The
public API, shape inference, parameters and checkpoints all stay NCHW
(reference parity); this pass rewrites only the *execution* inside the
graph runner, the way the reference's memory-plan/exec passes rewrite
execution without changing Symbol semantics.

Mechanics: the runner keeps an "is NHWC" tag per graph value.
``Convolution`` pulls its data input into NHWC and emits NHWC;
layout-flexible ops — BatchNorm, Pooling, LRN, activations, Dropout,
same-shape elementwise arithmetic, Concat/SliceChannel over the channel
axis — propagate the tag by running a channel-last variant (or their
stock elementwise kernel, which is layout-blind). Every other op forces
its inputs back to NCHW, so transposes appear only at layout-domain
boundaries: once at the first conv, and once where a layout-fixing op
(Flatten, FullyConnected, SoftmaxOutput, ...) consumes a spatial tensor
— in ResNet-50 that second boundary sits after global pooling where the
tensor is (N, 1, 1, C) and the transpose is free. XLA folds the
per-step OIHW->HWIO weight transposes into the convolution itself.

Kill switch: ``MXNET_NHWC_LAYOUT=0`` (the pass is on by default; the
monitor/NaiveEngine debug runners always run reference-layout NCHW).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import parse_tuple, parse_bool, parse_int, parse_float

__all__ = ["nhwc_exec", "to_nhwc", "to_nchw", "layout_opt_enabled"]


def layout_opt_enabled():
    import os
    return os.environ.get("MXNET_NHWC_LAYOUT", "1") != "0"


def to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _ntuple(v, n, default):
    t = parse_tuple(v) if v is not None else None
    if t is None:
        return (default,) * n
    if len(t) != n:
        t = tuple(t) + (default,) * (n - len(t))
    return t


# --------------------------------------------------------------------------
# channel-last kernels for the layout-entry / layout-flex ops
# --------------------------------------------------------------------------
def _conv_nhwc(attrs, data, weight, bias=None):
    """2-d Convolution on NHWC data; weight arrives in the reference's
    OIHW parameter layout and is transposed to HWIO here (folded into
    the conv by XLA)."""
    kernel = parse_tuple(attrs["kernel"])
    stride = _ntuple(attrs.get("stride"), 2, 1)
    pad = _ntuple(attrs.get("pad"), 2, 0)
    dilate = _ntuple(attrs.get("dilate"), 2, 1)
    ng = parse_int(attrs.get("num_group", 1))
    w = jnp.transpose(weight, (2, 3, 1, 0)).astype(data.dtype)
    dn = lax.conv_dimension_numbers(data.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    out = lax.conv_general_dilated(
        data, w, stride, [(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=ng)
    if bias is not None:
        out = out + bias.astype(data.dtype)   # broadcasts over minor C
    return out


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------
# shape-preserving elementwise binaries: layout-blind when every operand
# shares one layout (the runner converts minority-NCHW operands first)
_EW_BINARY = {"elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
              "_maximum", "_minimum", "_hypot", "_power"}
# shape-preserving single-input ops whose stock kernel never looks at the
# channel axis
_EW_UNARY = {"Activation", "Dropout", "_copy", "BlockGrad", "Cast",
             "relu", "sigmoid", "tanh", "exp", "sqrt", "square", "abs",
             "negative", "clip", "_add_scalar", "_minus_scalar",
             "_rminus_scalar", "_mul_scalar", "_div_scalar",
             "_rdiv_scalar", "_maximum_scalar", "_minimum_scalar",
             "_power_scalar"}


def nhwc_exec(opdef, attrs, regular, aux, in_tags, is_train, rng):
    """Try to execute one graph node channel-last.

    ``regular`` are the node's data inputs (possibly NHWC, per
    ``in_tags``); ``aux`` are its auxiliary states (always layout-free:
    per-channel vectors). Returns ``(outputs, new_aux, out_tags)`` or
    None, in which case the caller must convert NHWC inputs back to
    NCHW and run the stock kernel.
    """
    name = opdef.name

    if name == "Convolution":
        data = regular[0]
        if data.ndim != 4 or len(parse_tuple(attrs["kernel"])) != 2:
            return None
        if not in_tags[0]:
            data = to_nhwc(data)
        out = _conv_nhwc(attrs, data, *regular[1:])
        return [out], [], [True]

    # flex ops only continue an NHWC domain, never start one
    if name == "Pooling":
        if not in_tags[0] or regular[0].ndim != 4:
            return None
        from .nn import _pooling
        return [_pooling(attrs, regular[0], channel_axis=-1)], [], [True]

    if name == "BatchNorm":
        if not in_tags[0]:
            return None
        from .nn import _bn_fwd
        outs, new_aux = _bn_fwd(attrs, regular, aux, is_train, rng,
                                channel_axis=-1)
        return outs, new_aux, [True, False, False]

    if name == "LRN":
        if not in_tags[0] or regular[0].ndim != 4:
            return None
        from .nn import _lrn
        out, norm = _lrn(attrs, regular[0], channel_axis=-1)
        return [out, norm], [], [True, True]

    if name == "LeakyReLU":
        if not in_tags[0]:
            return None
        if attrs.get("act_type", "leaky") == "prelu":
            x = regular[0]
            gamma = regular[1].reshape((1,) * (x.ndim - 1) + (-1,))
            return [jnp.where(x > 0, x, gamma * x)], [], [True]
        outs, new_aux = opdef.forward(attrs, regular, aux, is_train, rng)
        return outs, new_aux, [True] * len(outs)

    if name == "Concat":
        dim = parse_int(attrs.get("dim", 1))
        if dim != 1 or not all(in_tags) or regular[0].ndim != 4:
            return None
        return [jnp.concatenate(regular, axis=3)], [], [True]

    if name == "SliceChannel":
        if not in_tags[0] or regular[0].ndim != 4 or \
                parse_int(attrs.get("axis", 1)) != 1 or \
                parse_bool(attrs.get("squeeze_axis", False)):
            return None
        n = parse_int(attrs.get("num_outputs", 1))
        outs = jnp.split(regular[0], n, axis=3)
        return list(outs), [], [True] * n

    if name in _EW_UNARY:
        if not in_tags[0]:
            return None
        outs, new_aux = opdef.forward(attrs, regular, aux, is_train, rng)
        # identity-shaped: every output inherits the input's layout
        return outs, new_aux, [True] * len(outs)

    if name in _EW_BINARY:
        if len(regular) != 2 or not any(in_tags[:2]):
            return None
        a, b = regular
        if a.ndim != 4 or a.shape != b.shape:
            return None
        if not in_tags[0]:
            a = to_nhwc(a)
        if not in_tags[1]:
            b = to_nhwc(b)
        outs, new_aux = opdef.forward(attrs, [a, b], aux, is_train, rng)
        return outs, new_aux, [True] * len(outs)

    return None
