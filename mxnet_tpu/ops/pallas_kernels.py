"""Production Pallas kernels, shipped through the kernel tier.

Three fused kernels the paper's L1 story names as "Pallas where XLA
fusion loses" (SURVEY §7), each registered as a ``variants["pallas"]``
alternative on an op whose ``forward`` stays the exact XLA composition:

* **fused softmax-cross-entropy** — a ``SoftmaxOutput`` variant: one
  row-block kernel for the forward softmax and one for the loss-head
  backward ``(p - onehot) * mask * scale`` (the op's custom-VJP
  contract: the incoming head cotangent is ignored);
* **fused conv+BN+ReLU** — a new ``FusedConvBNReLU`` op consuming the
  existing BatchNorm aux-state contract (moving_mean/moving_var swap
  after every training forward). The convolution itself stays on the
  MXU through ``lax.conv`` (XLA is already optimal there); the Pallas
  half fuses the whole BN epilogue — per-channel statistics reduction
  plus normalize+affine+ReLU — into two HBM passes instead of XLA's
  stat/normalize/activation chain;
* **fused optimizer updates** — ``sgd_mom_update`` (promoted from the
  rtc.py correctness demo) and ``adam_update`` variants: the whole
  elementwise update in one tiled VMEM pass per parameter.

The memory-bound sweep (ROADMAP 4) widened the tier with three more
families, each fusing what the roofline section of diagnose.py names as
HBM-round-trip chains:

* **fused LayerNorm** — a ``LayerNorm`` variant: one row-block VMEM
  pass for the forward (whole rows resident, f32 statistics) and
  hand-written backward kernels (a dx row pass plus a dgamma/dbeta
  accumulation pass) instead of XLA's mean/var/normalize chain;
* **fused bias+GeLU** — the ``FusedBiasGeLU`` op: the dense→GeLU
  epilogue as one VMEM pass (bias add + erf GeLU), with a hand dx
  kernel; composes with ``FullyConnected(no_bias=True)`` so the matmul
  output is touched exactly once more;
* **fused embedding lookup** — an ``Embedding`` variant: scalar-
  prefetched ids drive the weight BlockSpec's index map (one-pass
  gather + optional scale), backward is a scatter-add.

Every kernel carries a custom VJP. Where a hand backward kernel exists
(softmax-CE) it is used; elsewhere the backward recomputes through the
XLA composition under ``jax.custom_vjp`` (the flash-attention recompute
pattern — numerics match training through either tier by construction).
Selection is never static: the tier autotunes per shape on TPU and
falls back to XLA everywhere else (kernel_tier.py).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..base import parse_bool, parse_float, parse_int
from .registry import OP_REGISTRY, get_op, register

__all__ = ["pallas_call", "pallas_sgd_mom_update", "pallas_adam_update",
           "fused_softmax_ce", "fused_conv_bn_relu", "fused_layernorm",
           "fused_bias_gelu", "fused_embedding", "decode_attention"]


def _interpret():
    """Mosaic-compile on TPU; interpret elsewhere (CPU test mesh)."""
    return jax.default_backend() != "tpu"


def pallas_call(kernel, out_shape, **kwargs):
    """``pl.pallas_call`` with backend-appropriate compile/interpret."""
    kwargs.setdefault("interpret", _interpret())
    return pl.pallas_call(kernel, out_shape=out_shape, **kwargs)


def _divisor_block(n, cap):
    """Largest divisor of n that is <= cap (grid blocks must tile n)."""
    b = min(int(cap), int(n))
    while n % b:
        b -= 1
    return b


def _xla_recompute_vjp(pallas_fn, xla_fn, n_diff):
    """custom_vjp wrapper: Pallas forward, XLA-composition backward.

    ``n_diff`` positional args are differentiable; both fns map them to
    the same output pytree. The recompute keeps training numerics
    identical through either tier without a hand-written backward."""
    @jax.custom_vjp
    def fn(*args):
        return pallas_fn(*args)

    def fwd(*args):
        return fn(*args), args

    def bwd(args, cts):
        _, vjp_fn = jax.vjp(lambda *a: xla_fn(*a), *args[:n_diff])
        return vjp_fn(cts) + (None,) * (len(args) - n_diff)

    fn.defvjp(fwd, bwd)
    return fn


# ==========================================================================
# fused softmax cross-entropy (SoftmaxOutput pallas variant)
# ==========================================================================
def _softmax_fwd_kernel(x_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(
        o_ref.dtype)


def _softmax_ce_bwd_kernel(scale, use_ignore, ignore_label):
    def kernel(p_ref, l_ref, g_ref):
        p = p_ref[...].astype(jnp.float32)
        lab = l_ref[...].astype(jnp.int32)            # (block_n, 1)
        classes = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
        onehot = (classes == lab).astype(jnp.float32)
        g = p - onehot
        if use_ignore:
            keep = (l_ref[...].astype(jnp.float32) !=
                    ignore_label).astype(jnp.float32)
            g = g * keep                              # broadcasts (n, 1)
        g_ref[...] = (g * scale).astype(g_ref.dtype)
    return kernel


def _row_blocks(n, c):
    """Row-block size bounded by a ~2 MiB VMEM working set."""
    cap = max(8, (2 << 20) // max(1, 4 * c))
    return _divisor_block(n, min(256, cap))


def _pl_softmax(data):
    n, c = data.shape
    bn = _row_blocks(n, c)
    spec = pl.BlockSpec((bn, c), lambda i: (i, 0))
    return pallas_call(
        _softmax_fwd_kernel,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        grid=(n // bn,), in_specs=[spec], out_specs=spec)(data)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_ce_fn(data, label, attrs_tuple):
    return _pl_softmax(data)


def _softmax_ce_fwd(data, label, attrs_tuple):
    prob = _pl_softmax(data)
    return prob, (prob, label)


def _softmax_ce_bwd(attrs_tuple, res, g):
    # loss-head contract (ops/loss.py): the incoming cotangent is
    # ignored; the backward IS the cross-entropy gradient
    prob, label = res
    attrs = dict(attrs_tuple)
    grad_scale = parse_float(attrs.get("grad_scale", 1.0))
    use_ignore = parse_bool(attrs.get("use_ignore", False))
    ignore_label = parse_float(attrs.get("ignore_label", -1.0))
    normalization = attrs.get("normalization", "null")
    n, c = prob.shape
    scale = grad_scale / (n if normalization == "batch" else 1.0)
    bn = _row_blocks(n, c)
    lab2 = label.reshape(n, 1).astype(jnp.float32)
    grad = pallas_call(
        _softmax_ce_bwd_kernel(scale, use_ignore, ignore_label),
        out_shape=jax.ShapeDtypeStruct(prob.shape, prob.dtype),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)))(prob, lab2)
    if normalization == "valid":
        valid = jnp.sum((label != ignore_label).astype(jnp.float32)) \
            if use_ignore else jnp.asarray(float(n), jnp.float32)
        grad = grad / jnp.maximum(valid, 1.0).astype(grad.dtype)
    return grad, jnp.zeros_like(label)


_softmax_ce_fn.defvjp(_softmax_ce_fwd, _softmax_ce_bwd)


def fused_softmax_ce(data, label, **attrs):
    """Functional surface of the fused softmax-CE kernel (2-D data)."""
    return _softmax_ce_fn(data, label, tuple(sorted(attrs.items())))


def _softmax_ce_variant(attrs, inputs, aux, is_train, rng):
    data, label = inputs
    return [_softmax_ce_fn(data, label, tuple(sorted(attrs.items())))], []


def _softmax_ce_eligible(attrs, in_shapes, in_dtypes):
    if parse_bool(attrs.get("multi_output", False)):
        return False
    if len(in_shapes) < 2 or len(in_shapes[0]) != 2:
        return False
    n, c = in_shapes[0]
    if tuple(in_shapes[1]) != (n,):
        return False
    return c <= 65536 and str(in_dtypes[0]) in ("float32", "bfloat16",
                                                "float16")


#: worst-case VMEM residency at the eligibility bounds (c <= 65536 ->
#: 8-row blocks; small c -> 256-row blocks at ~2 MiB): prob in + out.
#: Validated at registration by analysis/kernelcheck.py (PK9xx).
_SOFTMAX_CE_KSPEC = {
    "tiles": [((8, 65536), "float32"), ((8, 65536), "float32")],
    "dtypes": ("float32", "bfloat16", "float16"),
}


# ==========================================================================
# fused conv + BatchNorm + ReLU
# ==========================================================================
def _bn_stats_kernel(x_ref, sum_ref, sq_ref):
    n = pl.program_id(1)
    xb = pl.program_id(2)

    @pl.when((n == 0) & (xb == 0))
    def _init():
        sum_ref[...] = jnp.zeros(sum_ref.shape, jnp.float32)
        sq_ref[...] = jnp.zeros(sq_ref.shape, jnp.float32)

    x = x_ref[...].astype(jnp.float32)                # (block_c, block_x)
    sum_ref[...] += jnp.sum(x, axis=-1)[None, :]
    sq_ref[...] += jnp.sum(x * x, axis=-1)[None, :]


def _bn_apply_relu_kernel(x_ref, scale_ref, shift_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)                # (block_c, block_x)
    scale = scale_ref[...].reshape(-1, 1)             # (block_c, 1)
    shift = shift_ref[...].reshape(-1, 1)
    o_ref[...] = jnp.maximum(x * scale + shift, 0.0).astype(o_ref.dtype)


def _channel_blocks(n, c, hw):
    block_c = _divisor_block(c, 128)
    cap_x = max(128, (2 << 20) // max(1, 4 * block_c))
    block_x = _divisor_block(hw, cap_x)
    return block_c, block_x


def _pl_channel_stats(x4):
    """Per-channel (sum, sum of squares) of an NCHW tensor, f32."""
    n, c, h, w = x4.shape
    hw = h * w
    x3 = x4.reshape(n, c, hw)
    block_c, block_x = _channel_blocks(n, c, hw)
    # channel blocks outermost so the (1, block_c) output tile stays
    # resident while the sequential grid walks batch and spatial blocks
    grid = (c // block_c, n, hw // block_x)
    in_spec = pl.BlockSpec((None, block_c, block_x),
                           lambda cb, nb, xb: (nb, cb, xb))
    out_spec = pl.BlockSpec((1, block_c), lambda cb, nb, xb: (0, cb))
    s, sq = pallas_call(
        _bn_stats_kernel,
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * 2,
        grid=grid, in_specs=[in_spec], out_specs=[out_spec, out_spec])(x3)
    return s.reshape(c), sq.reshape(c)


def _pl_apply_bn_relu(x4, scale, shift):
    n, c, h, w = x4.shape
    hw = h * w
    x3 = x4.reshape(n, c, hw)
    block_c, block_x = _channel_blocks(n, c, hw)
    grid = (n, c // block_c, hw // block_x)
    x_spec = pl.BlockSpec((None, block_c, block_x),
                          lambda nb, cb, xb: (nb, cb, xb))
    p_spec = pl.BlockSpec((1, block_c), lambda nb, cb, xb: (0, cb))
    out = pallas_call(
        _bn_apply_relu_kernel,
        out_shape=jax.ShapeDtypeStruct(x3.shape, x4.dtype),
        grid=grid, in_specs=[x_spec, p_spec, p_spec],
        out_specs=x_spec)(x3, scale.reshape(1, c), shift.reshape(1, c))
    return out.reshape(n, c, h, w)


_FUSED_CBR_ATTRS = None        # populated at registration below


def _cbr_conv(attrs, data, weight):
    from .nn import _convolution
    return _convolution(attrs, data, weight)


def _cbr_xla_impl(attrs, data, weight, gamma, beta, moving_mean,
                  moving_var, is_train):
    """The exact XLA composition: Convolution -> BatchNorm -> ReLU,
    sharing ops/nn.py's kernels so numerics are the composition's."""
    from .nn import _bn_fwd
    conv = _cbr_conv(attrs, data, weight)
    # _bn_fwd returns ([out, mean, var], [new_mean, new_var])
    outs, new_aux = _bn_fwd(attrs, [conv, gamma, beta],
                            [moving_mean, moving_var], is_train, None)
    y = jnp.maximum(outs[0], 0)
    return y, new_aux


def _cbr_scale_shift(attrs, gamma, mean, var, beta):
    eps = parse_float(attrs.get("eps", 1e-3))
    if parse_bool(attrs.get("fix_gamma", True)):
        gamma = jnp.ones_like(gamma)
    inv = jax.lax.rsqrt(var + eps)
    scale = (inv * gamma.astype(jnp.float32))
    shift = beta.astype(jnp.float32) - mean * scale
    return scale, shift


def _cbr_pallas_impl(attrs, data, weight, gamma, beta, moving_mean,
                     moving_var, is_train):
    conv = _cbr_conv(attrs, data, weight)
    use_global = parse_bool(attrs.get("use_global_stats", False))
    momentum = parse_float(attrs.get("momentum", 0.9))
    if is_train and not use_global:
        n, c, h, w = conv.shape
        cnt = float(n * h * w)
        s, sq = _pl_channel_stats(conv)
        mean = s / cnt
        var = jnp.maximum(sq / cnt - mean * mean, 0.0)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    scale, shift = _cbr_scale_shift(attrs, gamma, mean, var, beta)
    y = _pl_apply_bn_relu(conv, scale, shift)
    return y, [new_mean, new_var]


def _cbr_make(attrs, is_train):
    """custom_vjp closure over (static) attrs + train flag: Pallas
    forward emitting ``(y, new_mean, new_var)`` in one pass, backward
    recomputed through the XLA composition (aux cotangents discarded —
    moving statistics are side state, exactly as in BatchNorm)."""
    def xla_out(data, weight, gamma, beta, mm, mv):
        return _cbr_xla_impl(attrs, data, weight, gamma, beta,
                             jax.lax.stop_gradient(mm),
                             jax.lax.stop_gradient(mv), is_train)[0]

    @jax.custom_vjp
    def fn(data, weight, gamma, beta, mm, mv):
        y, new_aux = _cbr_pallas_impl(attrs, data, weight, gamma, beta,
                                      mm, mv, is_train)
        return y, new_aux[0], new_aux[1]

    def fwd(data, weight, gamma, beta, mm, mv):
        return fn(data, weight, gamma, beta, mm, mv), \
            (data, weight, gamma, beta, mm, mv)

    def bwd(res, cts):
        data, weight, gamma, beta, mm, mv = res
        ct_y = cts[0]                 # aux-state cotangents are zeros
        _, vjp_fn = jax.vjp(
            lambda d, w, g, b: xla_out(d, w, g, b, mm, mv),
            data, weight, gamma, beta)
        return vjp_fn(ct_y) + (jnp.zeros_like(mm), jnp.zeros_like(mv))

    fn.defvjp(fwd, bwd)
    return fn


def fused_conv_bn_relu(data, weight, gamma, beta, moving_mean,
                       moving_var, is_train=False, **attrs):
    """Functional surface of the fused conv+BN+ReLU Pallas kernel.

    Returns ``(out, [new_moving_mean, new_moving_var])`` — the same
    aux-state contract as BatchNorm (the executor swaps new aux after a
    training forward)."""
    y, nm, nv = _cbr_make(attrs, bool(is_train))(
        data, weight, gamma, beta, moving_mean, moving_var)
    return y, [nm, nv]


def _cbr_xla_variant(attrs, inputs, aux, is_train, rng):
    data, weight, gamma, beta = inputs
    y, new_aux = _cbr_xla_impl(attrs, data, weight, gamma, beta,
                               aux[0], aux[1], is_train)
    return [y], new_aux


def _cbr_pallas_variant(attrs, inputs, aux, is_train, rng):
    data, weight, gamma, beta = inputs
    y, nm, nv = _cbr_make(attrs, bool(is_train))(
        data, weight, gamma, beta, aux[0], aux[1])
    return [y], [nm, nv]


def _cbr_eligible(attrs, in_shapes, in_dtypes):
    kern = attrs.get("kernel")
    if kern is None or len(tuple(kern)) != 2:
        return False
    if len(in_shapes) < 1 or len(in_shapes[0]) != 4:
        return False
    return str(in_dtypes[0]) in ("float32", "bfloat16", "float16")


#: stats + normalize passes: (block_c<=128, block_x<=2MiB/4/block_c)
#: data tile twice resident (in + normalized out) plus the per-channel
#: accumulator rows
_CBR_KSPEC = {
    "tiles": [((128, 4096), "float32"), ((128, 4096), "float32"),
              ((8, 128), "float32")],
    "dtypes": ("float32", "bfloat16", "float16"),
}


def _cbr_infer(attrs, in_shapes):
    from .nn import _conv_infer
    conv_attrs = dict(attrs, no_bias=True)
    new_in, out_s, _ = _conv_infer(conv_attrs, in_shapes[:2])
    nf = parse_int(attrs["num_filter"])
    c = (nf,)
    return [new_in[0], new_in[1], c, c], out_s, [c, c]


def _register_fused_conv_bn_relu():
    if "FusedConvBNReLU" in OP_REGISTRY:
        return
    from .nn import _CONV_ATTRS
    attrs = {k: v for k, v in _CONV_ATTRS.items() if k != "no_bias"}
    attrs.update({"eps": (parse_float, 1e-3),
                  "momentum": (parse_float, 0.9),
                  "fix_gamma": (parse_bool, True),
                  "use_global_stats": (parse_bool, False)})
    register("FusedConvBNReLU",
             inputs=("data", "weight", "gamma", "beta"),
             aux=("moving_mean", "moving_var"),
             full=_cbr_xla_variant,
             attr_spec=attrs, infer_shape=_cbr_infer,
             variants={"pallas": (_cbr_pallas_variant, _cbr_eligible,
                                  _CBR_KSPEC)})


_register_fused_conv_bn_relu()


# ==========================================================================
# fused optimizer updates (promoted from rtc.py's correctness demo)
# ==========================================================================
_TILE_ROWS = 256
_LANES = 128


def _pad_to_tiles(v):
    n = v.size
    cols = _LANES
    rows = -(-n // cols)
    rows_pad = -(-rows // 16) * 16        # bf16-safe sublane multiple
    flat = jnp.ravel(v)
    flat = jnp.pad(flat, (0, rows_pad * cols - n))
    return flat.reshape(rows_pad, cols), n


def _tiled_elementwise(kernel, arrays, n_out):
    """Run an elementwise kernel over same-shaped operands: flatten,
    pad to (16k, 128) tiles, grid over row blocks, un-pad."""
    shape = arrays[0].shape
    padded = []
    n = None
    for a in arrays:
        p, n = _pad_to_tiles(a)
        padded.append(p)
    rows = padded[0].shape[0]
    # block rows: a 16-multiple divisor so the grid tiles rows exactly
    block = 16 * _divisor_block(rows // 16, _TILE_ROWS // 16)
    spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    outs = pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(padded[0].shape,
                                        padded[0].dtype)] * n_out,
        grid=(rows // block,),
        in_specs=[spec] * len(padded),
        out_specs=[spec] * n_out)(*padded)
    return tuple(o.reshape(-1)[:n].reshape(shape) for o in outs)


def _hyper(attrs):
    lr = parse_float(attrs["lr"])
    wd = parse_float(attrs.get("wd", 0.0))
    rescale = parse_float(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_gradient")
    clip = parse_float(clip) if clip is not None and \
        parse_float(clip) > 0 else None
    return lr, wd, rescale, clip


def _prep(g, w, wd, rescale, clip):
    g = g * rescale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    return g + wd * w


def _sgd_mom_kernel(attrs):
    lr, wd, rescale, clip = _hyper(attrs)
    momentum = parse_float(attrs.get("momentum", 0.0))

    def kernel(w_ref, g_ref, m_ref, ow_ref, om_ref):
        g = _prep(g_ref[...], w_ref[...], wd, rescale, clip)
        m = momentum * m_ref[...] - lr * g
        om_ref[...] = m
        ow_ref[...] = w_ref[...] + m
    return kernel


def _adam_kernel(attrs):
    lr, wd, rescale, clip = _hyper(attrs)
    b1 = parse_float(attrs.get("beta1", 0.9))
    b2 = parse_float(attrs.get("beta2", 0.999))
    eps = parse_float(attrs.get("epsilon", 1e-8))

    def kernel(w_ref, g_ref, mean_ref, var_ref, ow_ref, omean_ref,
               ovar_ref):
        w = w_ref[...]
        g = _prep(g_ref[...], w, wd, rescale, clip)
        mean = b1 * mean_ref[...] + (1 - b1) * g
        var = b2 * var_ref[...] + (1 - b2) * g * g
        omean_ref[...] = mean
        ovar_ref[...] = var
        ow_ref[...] = w - lr * mean / (jnp.sqrt(var) + eps)
    return kernel


def pallas_sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                          rescale_grad=1.0, clip_gradient=None):
    """Fused SGD-momentum update on jax arrays: (weight', mom')."""
    attrs = {"lr": lr, "momentum": momentum, "wd": wd,
             "rescale_grad": rescale_grad, "clip_gradient": clip_gradient}
    return _tiled_elementwise(_sgd_mom_kernel(attrs),
                              [weight, grad, mom], 2)


def pallas_adam_update(weight, grad, mean, var, lr, beta1=0.9,
                       beta2=0.999, epsilon=1e-8, wd=0.0,
                       rescale_grad=1.0, clip_gradient=None):
    """Fused Adam update on jax arrays: (weight', mean', var')."""
    attrs = {"lr": lr, "beta1": beta1, "beta2": beta2, "epsilon": epsilon,
             "wd": wd, "rescale_grad": rescale_grad,
             "clip_gradient": clip_gradient}
    return _tiled_elementwise(_adam_kernel(attrs),
                              [weight, grad, mean, var], 3)


def _opt_variant(op_name, kernel_builder, n_in, n_out):
    """Pallas variant of a registered optimizer op, with the uniform
    XLA-recompute custom VJP (updates are rarely differentiated, but
    the contract holds through either tier)."""
    xla_fwd = get_op(op_name).forward

    def variant(attrs, inputs, aux, is_train, rng):
        def pallas_fn(*vals):
            return _tiled_elementwise(kernel_builder(attrs), list(vals),
                                      n_out)

        def xla_fn(*vals):
            outs, _ = xla_fwd(attrs, list(vals), [], is_train, rng)
            return tuple(outs)

        fn = _xla_recompute_vjp(pallas_fn, xla_fn, n_in)
        return list(fn(*inputs)), []

    def eligible(attrs, in_shapes, in_dtypes):
        if len(set(tuple(s) for s in in_shapes)) != 1:
            return False
        return all(str(d) in ("float32", "bfloat16", "float16")
                   for d in in_dtypes)

    return variant, eligible


def _opt_kspec(n_arrays):
    """n_arrays (256, 128) f32 tiles resident per grid step — the
    flattened elementwise update's whole working set."""
    return {"tiles": [((_TILE_ROWS, _LANES), "float32")] * n_arrays,
            "dtypes": ("float32", "bfloat16", "float16")}


# ==========================================================================
# fused LayerNorm (LayerNorm pallas variant): one VMEM pass forward
# (whole rows resident, f32 statistics), hand-written backward kernels
# ==========================================================================
def _ln_fwd_kernel(eps):
    def kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref):
        x = x_ref[...].astype(jnp.float32)            # (block_n, C)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        d = x - mean
        var = jnp.mean(d * d, axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        g = g_ref[...].astype(jnp.float32)            # (1, C)
        b = b_ref[...].astype(jnp.float32)
        y_ref[...] = (d * rstd * g + b).astype(y_ref.dtype)
        mean_ref[...] = mean
        rstd_ref[...] = rstd
    return kernel


def _ln_bwd_dx_kernel(x_ref, g_ref, ct_ref, mean_ref, rstd_ref, dx_ref):
    x = x_ref[...].astype(jnp.float32)
    ct = ct_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)                # (1, C)
    rstd = rstd_ref[...]                              # (block_n, 1)
    xh = (x - mean_ref[...]) * rstd
    gy = ct * g
    m1 = jnp.mean(gy, axis=-1, keepdims=True)
    m2 = jnp.mean(gy * xh, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gy - m1 - xh * m2)).astype(dx_ref.dtype)


def _ln_bwd_dparams_kernel(x_ref, ct_ref, mean_ref, rstd_ref,
                           dg_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros(dg_ref.shape, jnp.float32)
        db_ref[...] = jnp.zeros(db_ref.shape, jnp.float32)

    x = x_ref[...].astype(jnp.float32)
    ct = ct_ref[...].astype(jnp.float32)
    xh = (x - mean_ref[...]) * rstd_ref[...]
    dg_ref[...] += jnp.sum(ct * xh, axis=0)[None, :]
    db_ref[...] += jnp.sum(ct, axis=0)[None, :]


def _ln_specs(n, c):
    bn = _row_blocks(n, c)
    row = pl.BlockSpec((bn, c), lambda i: (i, 0))
    stat = pl.BlockSpec((bn, 1), lambda i: (i, 0))
    par = pl.BlockSpec((1, c), lambda i: (0, 0))
    return bn, row, stat, par


def _pl_layernorm_fwd(x2, gamma, beta, eps):
    n, c = x2.shape
    bn, row, stat, par = _ln_specs(n, c)
    f32 = jnp.float32
    return pallas_call(
        _ln_fwd_kernel(eps),
        out_shape=[jax.ShapeDtypeStruct((n, c), x2.dtype),
                   jax.ShapeDtypeStruct((n, 1), f32),
                   jax.ShapeDtypeStruct((n, 1), f32)],
        grid=(n // bn,), in_specs=[row, par, par],
        out_specs=[row, stat, stat])(
            x2, gamma.reshape(1, c), beta.reshape(1, c))


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_pl_fn(x2, gamma, beta, eps):
    return _pl_layernorm_fwd(x2, gamma, beta, eps)


def _ln_pl_fwd_rule(x2, gamma, beta, eps):
    y, mean, rstd = _pl_layernorm_fwd(x2, gamma, beta, eps)
    return (y, mean, rstd), (x2, gamma, mean, rstd)


def _ln_pl_bwd_rule(eps, res, cts):
    # mean/std are statistic outputs (hidden unless output_mean_var);
    # their cotangents are treated as zero, like BatchNorm's
    x2, gamma, mean, rstd = res
    ct = cts[0]
    n, c = x2.shape
    bn, row, stat, par = _ln_specs(n, c)
    dx = pallas_call(
        _ln_bwd_dx_kernel,
        out_shape=jax.ShapeDtypeStruct((n, c), x2.dtype),
        grid=(n // bn,), in_specs=[row, par, row, stat, stat],
        out_specs=row)(x2, gamma.reshape(1, c), ct, mean, rstd)
    dg, db = pallas_call(
        _ln_bwd_dparams_kernel,
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * 2,
        grid=(n // bn,), in_specs=[row, row, stat, stat],
        out_specs=[pl.BlockSpec((1, c), lambda i: (0, 0))] * 2)(
            x2, ct, mean, rstd)
    return (dx, dg.reshape(c).astype(gamma.dtype),
            db.reshape(c).astype(gamma.dtype))


_ln_pl_fn.defvjp(_ln_pl_fwd_rule, _ln_pl_bwd_rule)


def fused_layernorm(data, gamma, beta, eps=1e-5):
    """Functional surface of the fused LayerNorm kernel (last axis).

    Returns ``(out, mean, std)`` with mean/std shaped like
    ``data.shape[:-1]`` — the LayerNorm op's output contract."""
    c = data.shape[-1]
    x2 = data.reshape(-1, c)
    y, mean, rstd = _ln_pl_fn(x2, gamma, beta, float(eps))
    lead = data.shape[:-1]
    return (y.reshape(data.shape), mean.reshape(lead),
            (1.0 / rstd).reshape(lead))


def _layernorm_variant(attrs, inputs, aux, is_train, rng):
    data, gamma, beta = inputs
    eps = parse_float(attrs.get("eps", 1e-5))
    y, mean, std = fused_layernorm(data, gamma, beta, eps)
    return [y, mean, std], []


def _layernorm_eligible(attrs, in_shapes, in_dtypes):
    data_s = in_shapes[0]
    if len(data_s) < 2:
        return False
    axis = parse_int(attrs.get("axis", -1))
    if axis not in (-1, len(data_s) - 1):
        return False
    return data_s[-1] <= 65536 and str(in_dtypes[0]) in (
        "float32", "bfloat16", "float16")


#: whole rows resident (C <= 65536 -> 8-row blocks): x in, y out, and
#: the f32 statistics columns
_LN_KSPEC = {
    "tiles": [((8, 65536), "float32"), ((8, 65536), "float32"),
              ((8, 128), "float32")],
    "dtypes": ("float32", "bfloat16", "float16"),
}


def _register_layernorm_variant():
    ln = get_op("LayerNorm")
    if "pallas" not in ln.variants:
        ln.add_variant("pallas", _layernorm_variant,
                       eligible=_layernorm_eligible,
                       kernel_spec=_LN_KSPEC)


# ==========================================================================
# fused bias + GeLU epilogue (FusedBiasGeLU op): the dense→GeLU pattern
# collapses to ONE VMEM pass over the matmul output instead of XLA's
# bias-add / erf / mul chain each re-touching HBM
# ==========================================================================
_INV_SQRT2 = 0.7071067811865476
_INV_SQRT2PI = 0.3989422804014327


def _bias_gelu_core(x32):
    return 0.5 * x32 * (1.0 + jax.lax.erf(x32 * _INV_SQRT2))


def _bias_gelu_kernel(x_ref, b_ref, o_ref):
    x = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = _bias_gelu_core(x).astype(o_ref.dtype)


def _bias_gelu_dx_kernel(x_ref, b_ref, ct_ref, dx_ref):
    z = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    phi = jnp.exp(-0.5 * z * z) * _INV_SQRT2PI
    dgelu = 0.5 * (1.0 + jax.lax.erf(z * _INV_SQRT2)) + z * phi
    dx_ref[...] = (ct_ref[...].astype(jnp.float32) * dgelu).astype(
        dx_ref.dtype)


def _pl_bias_gelu(x2, bias, kernel):
    n, c = x2.shape
    bn, row, _stat, par = _ln_specs(n, c)
    in_specs = [row, par] + ([row] if kernel is _bias_gelu_dx_kernel
                             else [])

    def call(*ops):
        return pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct((n, c), x2.dtype),
            grid=(n // bn,), in_specs=in_specs, out_specs=row)(*ops)
    return call


@jax.custom_vjp
def _bias_gelu_fn(x2, bias):
    return _pl_bias_gelu(x2, bias, _bias_gelu_kernel)(
        x2, bias.reshape(1, -1))


def _bias_gelu_fwd_rule(x2, bias):
    return _bias_gelu_fn(x2, bias), (x2, bias)


def _bias_gelu_bwd_rule(res, ct):
    x2, bias = res
    # dx: one hand-written VMEM pass; dbias: the (C,) column reduce of
    # dx, left to XLA (a single well-fused reduction)
    dx = _pl_bias_gelu(x2, bias, _bias_gelu_dx_kernel)(
        x2, bias.reshape(1, -1), ct)
    db = jnp.sum(dx.astype(jnp.float32), axis=0).astype(bias.dtype)
    return dx, db


_bias_gelu_fn.defvjp(_bias_gelu_fwd_rule, _bias_gelu_bwd_rule)


def fused_bias_gelu(data, bias):
    """Functional surface of the fused bias+GeLU epilogue kernel."""
    c = data.shape[-1]
    return _bias_gelu_fn(data.reshape(-1, c), bias).reshape(data.shape)


def _bias_gelu_xla(attrs, data, bias):
    # the exact composition (bias add + erf GeLU), accumulated in f32
    # like the kernel so both tiers share one numeric definition
    bshape = (1,) * (data.ndim - 1) + (-1,)
    x32 = data.astype(jnp.float32) + \
        bias.astype(jnp.float32).reshape(bshape)
    return _bias_gelu_core(x32).astype(data.dtype)


def _bias_gelu_variant(attrs, inputs, aux, is_train, rng):
    data, bias = inputs
    return [fused_bias_gelu(data, bias)], []


def _bias_gelu_eligible(attrs, in_shapes, in_dtypes):
    data_s, bias_s = in_shapes[0], in_shapes[1]
    if len(data_s) < 2 or tuple(bias_s) != (data_s[-1],):
        return False
    return data_s[-1] <= 65536 and str(in_dtypes[0]) in (
        "float32", "bfloat16", "float16")


def _bias_gelu_infer(attrs, in_shapes, out_known=None):
    data_s = in_shapes[0]
    if out_known and out_known[0] is not None and data_s is None:
        data_s = out_known[0]
    c = (data_s[-1],) if data_s is not None else None
    return [data_s, c], [data_s], []


#: row blocks with whole channels resident (C <= 65536): x, bias
#: broadcast rows, and the GeLU output
_BIAS_GELU_KSPEC = {
    "tiles": [((8, 65536), "float32"), ((8, 65536), "float32"),
              ((8, 65536), "float32")],
    "dtypes": ("float32", "bfloat16", "float16"),
}


def _register_bias_gelu():
    if "FusedBiasGeLU" in OP_REGISTRY:
        return
    register("FusedBiasGeLU", inputs=("data", "bias"),
             simple=_bias_gelu_xla, infer_shape=_bias_gelu_infer,
             variants={"pallas": (_bias_gelu_variant,
                                  _bias_gelu_eligible,
                                  _BIAS_GELU_KSPEC)})


_register_bias_gelu()


# ==========================================================================
# fused embedding lookup (Embedding pallas variant): one-pass gather
# (+ optional scale) driven by scalar-prefetched ids — the row index IS
# the weight BlockSpec's index_map — with a scatter-add backward
# ==========================================================================
def _emb_gather_kernel(scale):
    def kernel(ids_ref, w_ref, o_ref):
        x = w_ref[...]
        if scale != 1.0:
            x = (x.astype(jnp.float32) * scale).astype(o_ref.dtype)
        o_ref[...] = x
    return kernel


def _pl_embedding(ids, weight, scale):
    from jax.experimental.pallas import tpu as pltpu
    n = ids.shape[0]
    _v, d = weight.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(n,),
        in_specs=[pl.BlockSpec((1, d), lambda i, ids_ref:
                               (ids_ref[i], 0))],
        out_specs=pl.BlockSpec((1, d), lambda i, ids_ref: (i, 0)))
    return pallas_call(
        _emb_gather_kernel(scale),
        out_shape=jax.ShapeDtypeStruct((n, d), weight.dtype),
        grid_spec=grid_spec)(ids, weight)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _emb_fn(data, weight, scale):
    ids = data.astype(jnp.int32).ravel()
    out = _pl_embedding(ids, weight, scale)
    return out.reshape(tuple(data.shape) + (weight.shape[1],))


def _emb_fwd_rule(data, weight, scale):
    # weight rides the residuals only for its shape/dtype (it is a live
    # parameter either way — no extra buffer is stored)
    return _emb_fn(data, weight, scale), (data, weight)


def _emb_bwd_rule(scale, res, ct):
    data, weight = res
    ids = data.astype(jnp.int32).ravel()
    ct32 = ct.reshape(-1, weight.shape[1]).astype(jnp.float32)
    if scale != 1.0:
        ct32 = ct32 * scale
    dw = jnp.zeros(weight.shape, jnp.float32).at[ids].add(ct32)
    return jnp.zeros_like(data), dw.astype(weight.dtype)


_emb_fn.defvjp(_emb_fwd_rule, _emb_bwd_rule)


def fused_embedding(data, weight, scale=1.0):
    """Functional surface of the fused embedding-lookup kernel."""
    return _emb_fn(data, weight, float(scale))


def _embedding_variant(attrs, inputs, aux, is_train, rng):
    data, weight = inputs
    return [_emb_fn(data, weight,
                    parse_float(attrs.get("scale", 1.0)))], []


def _embedding_eligible(attrs, in_shapes, in_dtypes):
    w_s = in_shapes[1] if len(in_shapes) > 1 else None
    if w_s is None or len(w_s) != 2 or len(in_shapes[0]) < 1:
        return False
    if str(in_dtypes[1]) not in ("float32", "bfloat16", "float16"):
        return False
    if w_s[1] > 16384:
        # one looked-up row must fit the declared VMEM tile (PK901's
        # eligibility-side bound; wider tables keep the XLA gather)
        return False
    # Mosaic wants lane-aligned rows; interpret mode (off-TPU) takes any
    return w_s[1] % 128 == 0 or _interpret()


#: one prefetched row in, one out, at the D <= 16384 eligibility bound
_EMB_KSPEC = {
    "tiles": [((8, 16384), "float32"), ((8, 16384), "float32")],
    "dtypes": ("float32", "bfloat16", "float16"),
}


def _register_embedding_variant():
    emb = get_op("Embedding")
    if "pallas" not in emb.variants:
        emb.add_variant("pallas", _embedding_variant,
                        eligible=_embedding_eligible,
                        kernel_spec=_EMB_KSPEC)


# ==========================================================================
# flash-decode attention (the attention_decode pallas variant — rtc.py
# owns the op, the RoPE/cache-write prologue, and the registration; the
# kernel here is only the cursor-bounded attention READ)
# ==========================================================================
def _decode_attn_kernel(block_k, s_len, scale):
    def kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s):
        b = pl.program_id(0)
        kb = pl.program_id(1)
        n_kb = pl.num_programs(1)

        @pl.when(kb == 0)
        def _init():
            m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
            l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
            acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

        cursor = pos_ref[b]                  # this row's write position
        k_start = kb * block_k

        def update():
            q = q_ref[...].astype(jnp.float32) * scale     # (S, Dh)
            k = k_ref[...].astype(jnp.float32)             # (block_k, Dh)
            v = v_ref[...].astype(jnp.float32)
            # HIGHEST: match the XLA composition's f32 accumulation;
            # the astype above is also the fp8-cache dequant on read
            s = jnp.dot(q, k.T, precision=jax.lax.Precision.HIGHEST)
            # query row i sits at stream position cursor + i and attends
            # key positions <= that (the same comparison as the XLA mask)
            q_pos = cursor + jax.lax.broadcasted_iota(
                jnp.int32, (s_len, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (s_len, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
            m = m_s[...]                     # (S, 1) f32
            m_blk = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_blk)
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - m_safe)
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
            m_s[...] = m_new
            l_s[...] = l_s[...] * corr + jnp.sum(p, axis=-1,
                                                 keepdims=True)
            acc_s[...] = acc_s[...] * corr + jnp.dot(
                p, v, precision=jax.lax.Precision.HIGHEST)

        # blocks wholly past the live prefix [0, cursor + S) mask to
        # nothing: skip their FLOPs (their index map also re-points at
        # the last live block, so they cost no HBM traffic either).
        # Block 0 always runs — cursor >= 0 keys at least one position,
        # so l is never zero at emit.
        pl.when(k_start <= cursor + s_len - 1)(update)

        @pl.when(kb == n_kb - 1)
        def _emit():
            l = jnp.maximum(l_s[...], 1e-30)
            o_ref[...] = (acc_s[...] / l).astype(o_ref.dtype)
    return kernel


def decode_attention(q, k_cache, v_cache, pos, block_k=128):
    """Cursor-bounded flash-decode read over a fixed-capacity KV cache.

    ``q`` is (B, H, S, Dh) already-rotated queries, the caches are
    (B, H, C, Dh) with the step's rows already written, and ``pos`` is
    the (B,) per-row cursor (a scalar-cursor engine broadcasts before
    calling). The per-(b, h) grid row walks C // block_k cache blocks,
    but the scalar-prefetched cursor clamps the K/V index maps to the
    last live block — dead blocks re-reference an already-resident
    index, so HBM traffic is proportional to the live prefix
    ``[0, cursor_b + S)``, not the capacity. Online-softmax (m, l, acc)
    accumulates in f32 VMEM scratch; fp8 cache rows dequantize on read
    inside the kernel. Returns f32 (B, H, S, Dh) — the caller casts.
    """
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, Dh = q.shape
    C = k_cache.shape[2]
    block_k = _divisor_block(C, block_k)
    scale = float(Dh) ** -0.5
    qf = q.reshape(B * H, S, Dh)
    kf = k_cache.reshape(B * H, C, Dh)
    vf = v_cache.reshape(B * H, C, Dh)
    # row cursor per (b, h) pair, b-major to match the reshape order
    pos_bh = jnp.repeat(pos.astype(jnp.int32), H)

    def _kv_map(b, j, pos_ref):
        last_live = (pos_ref[b] + (S - 1)) // block_k
        return (b, jnp.minimum(j, last_live), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(B * H, C // block_k),
        in_specs=[
            pl.BlockSpec((None, S, Dh), lambda b, j, pos_ref: (b, 0, 0)),
            pl.BlockSpec((None, block_k, Dh), _kv_map),
            pl.BlockSpec((None, block_k, Dh), _kv_map),
        ],
        out_specs=pl.BlockSpec((None, S, Dh),
                               lambda b, j, pos_ref: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((S, 1), jnp.float32),
                        pltpu.VMEM((S, 1), jnp.float32),
                        pltpu.VMEM((S, Dh), jnp.float32)])
    out = pallas_call(
        _decode_attn_kernel(block_k, S, scale),
        out_shape=jax.ShapeDtypeStruct((B * H, S, Dh), jnp.float32),
        grid_spec=grid_spec)(pos_bh, qf, kf, vf)
    return out.reshape(B, H, S, Dh)


def _register_opt_variants():
    sgd = get_op("sgd_mom_update")
    if "pallas" not in sgd.variants:
        sgd.add_variant("pallas",
                        *_opt_variant("sgd_mom_update", _sgd_mom_kernel,
                                      3, 2),
                        kernel_spec=_opt_kspec(5))
    adam = get_op("adam_update")
    if "pallas" not in adam.variants:
        adam.add_variant("pallas",
                         *_opt_variant("adam_update", _adam_kernel, 4, 3),
                         kernel_spec=_opt_kspec(7))


def _register_softmax_ce_variant():
    sm = get_op("SoftmaxOutput")
    if "pallas" not in sm.variants:
        sm.add_variant("pallas", _softmax_ce_variant,
                       eligible=_softmax_ce_eligible,
                       kernel_spec=_SOFTMAX_CE_KSPEC)


_register_opt_variants()
_register_softmax_ce_variant()
_register_layernorm_variant()
_register_embedding_variant()
