"""Production Pallas kernels, shipped through the kernel tier.

Three fused kernels the paper's L1 story names as "Pallas where XLA
fusion loses" (SURVEY §7), each registered as a ``variants["pallas"]``
alternative on an op whose ``forward`` stays the exact XLA composition:

* **fused softmax-cross-entropy** — a ``SoftmaxOutput`` variant: one
  row-block kernel for the forward softmax and one for the loss-head
  backward ``(p - onehot) * mask * scale`` (the op's custom-VJP
  contract: the incoming head cotangent is ignored);
* **fused conv+BN+ReLU** — a new ``FusedConvBNReLU`` op consuming the
  existing BatchNorm aux-state contract (moving_mean/moving_var swap
  after every training forward). The convolution itself stays on the
  MXU through ``lax.conv`` (XLA is already optimal there); the Pallas
  half fuses the whole BN epilogue — per-channel statistics reduction
  plus normalize+affine+ReLU — into two HBM passes instead of XLA's
  stat/normalize/activation chain;
* **fused optimizer updates** — ``sgd_mom_update`` (promoted from the
  rtc.py correctness demo) and ``adam_update`` variants: the whole
  elementwise update in one tiled VMEM pass per parameter.

Every kernel carries a custom VJP. Where a hand backward kernel exists
(softmax-CE) it is used; elsewhere the backward recomputes through the
XLA composition under ``jax.custom_vjp`` (the flash-attention recompute
pattern — numerics match training through either tier by construction).
Selection is never static: the tier autotunes per shape on TPU and
falls back to XLA everywhere else (kernel_tier.py).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..base import parse_bool, parse_float, parse_int
from .registry import OP_REGISTRY, get_op, register

__all__ = ["pallas_call", "pallas_sgd_mom_update", "pallas_adam_update",
           "fused_softmax_ce", "fused_conv_bn_relu"]


def _interpret():
    """Mosaic-compile on TPU; interpret elsewhere (CPU test mesh)."""
    return jax.default_backend() != "tpu"


def pallas_call(kernel, out_shape, **kwargs):
    """``pl.pallas_call`` with backend-appropriate compile/interpret."""
    kwargs.setdefault("interpret", _interpret())
    return pl.pallas_call(kernel, out_shape=out_shape, **kwargs)


def _divisor_block(n, cap):
    """Largest divisor of n that is <= cap (grid blocks must tile n)."""
    b = min(int(cap), int(n))
    while n % b:
        b -= 1
    return b


def _xla_recompute_vjp(pallas_fn, xla_fn, n_diff):
    """custom_vjp wrapper: Pallas forward, XLA-composition backward.

    ``n_diff`` positional args are differentiable; both fns map them to
    the same output pytree. The recompute keeps training numerics
    identical through either tier without a hand-written backward."""
    @jax.custom_vjp
    def fn(*args):
        return pallas_fn(*args)

    def fwd(*args):
        return fn(*args), args

    def bwd(args, cts):
        _, vjp_fn = jax.vjp(lambda *a: xla_fn(*a), *args[:n_diff])
        return vjp_fn(cts) + (None,) * (len(args) - n_diff)

    fn.defvjp(fwd, bwd)
    return fn


# ==========================================================================
# fused softmax cross-entropy (SoftmaxOutput pallas variant)
# ==========================================================================
def _softmax_fwd_kernel(x_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(
        o_ref.dtype)


def _softmax_ce_bwd_kernel(scale, use_ignore, ignore_label):
    def kernel(p_ref, l_ref, g_ref):
        p = p_ref[...].astype(jnp.float32)
        lab = l_ref[...].astype(jnp.int32)            # (block_n, 1)
        classes = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
        onehot = (classes == lab).astype(jnp.float32)
        g = p - onehot
        if use_ignore:
            keep = (l_ref[...].astype(jnp.float32) !=
                    ignore_label).astype(jnp.float32)
            g = g * keep                              # broadcasts (n, 1)
        g_ref[...] = (g * scale).astype(g_ref.dtype)
    return kernel


def _row_blocks(n, c):
    """Row-block size bounded by a ~2 MiB VMEM working set."""
    cap = max(8, (2 << 20) // max(1, 4 * c))
    return _divisor_block(n, min(256, cap))


def _pl_softmax(data):
    n, c = data.shape
    bn = _row_blocks(n, c)
    spec = pl.BlockSpec((bn, c), lambda i: (i, 0))
    return pallas_call(
        _softmax_fwd_kernel,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        grid=(n // bn,), in_specs=[spec], out_specs=spec)(data)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_ce_fn(data, label, attrs_tuple):
    return _pl_softmax(data)


def _softmax_ce_fwd(data, label, attrs_tuple):
    prob = _pl_softmax(data)
    return prob, (prob, label)


def _softmax_ce_bwd(attrs_tuple, res, g):
    # loss-head contract (ops/loss.py): the incoming cotangent is
    # ignored; the backward IS the cross-entropy gradient
    prob, label = res
    attrs = dict(attrs_tuple)
    grad_scale = parse_float(attrs.get("grad_scale", 1.0))
    use_ignore = parse_bool(attrs.get("use_ignore", False))
    ignore_label = parse_float(attrs.get("ignore_label", -1.0))
    normalization = attrs.get("normalization", "null")
    n, c = prob.shape
    scale = grad_scale / (n if normalization == "batch" else 1.0)
    bn = _row_blocks(n, c)
    lab2 = label.reshape(n, 1).astype(jnp.float32)
    grad = pallas_call(
        _softmax_ce_bwd_kernel(scale, use_ignore, ignore_label),
        out_shape=jax.ShapeDtypeStruct(prob.shape, prob.dtype),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, c), lambda i: (i, 0)),
                  pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)))(prob, lab2)
    if normalization == "valid":
        valid = jnp.sum((label != ignore_label).astype(jnp.float32)) \
            if use_ignore else jnp.asarray(float(n), jnp.float32)
        grad = grad / jnp.maximum(valid, 1.0).astype(grad.dtype)
    return grad, jnp.zeros_like(label)


_softmax_ce_fn.defvjp(_softmax_ce_fwd, _softmax_ce_bwd)


def fused_softmax_ce(data, label, **attrs):
    """Functional surface of the fused softmax-CE kernel (2-D data)."""
    return _softmax_ce_fn(data, label, tuple(sorted(attrs.items())))


def _softmax_ce_variant(attrs, inputs, aux, is_train, rng):
    data, label = inputs
    return [_softmax_ce_fn(data, label, tuple(sorted(attrs.items())))], []


def _softmax_ce_eligible(attrs, in_shapes, in_dtypes):
    if parse_bool(attrs.get("multi_output", False)):
        return False
    if len(in_shapes) < 2 or len(in_shapes[0]) != 2:
        return False
    n, c = in_shapes[0]
    if tuple(in_shapes[1]) != (n,):
        return False
    return c <= 65536 and str(in_dtypes[0]) in ("float32", "bfloat16",
                                                "float16")


# ==========================================================================
# fused conv + BatchNorm + ReLU
# ==========================================================================
def _bn_stats_kernel(x_ref, sum_ref, sq_ref):
    n = pl.program_id(1)
    xb = pl.program_id(2)

    @pl.when((n == 0) & (xb == 0))
    def _init():
        sum_ref[...] = jnp.zeros(sum_ref.shape, jnp.float32)
        sq_ref[...] = jnp.zeros(sq_ref.shape, jnp.float32)

    x = x_ref[...].astype(jnp.float32)                # (block_c, block_x)
    sum_ref[...] += jnp.sum(x, axis=-1)[None, :]
    sq_ref[...] += jnp.sum(x * x, axis=-1)[None, :]


def _bn_apply_relu_kernel(x_ref, scale_ref, shift_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)                # (block_c, block_x)
    scale = scale_ref[...].reshape(-1, 1)             # (block_c, 1)
    shift = shift_ref[...].reshape(-1, 1)
    o_ref[...] = jnp.maximum(x * scale + shift, 0.0).astype(o_ref.dtype)


def _channel_blocks(n, c, hw):
    block_c = _divisor_block(c, 128)
    cap_x = max(128, (2 << 20) // max(1, 4 * block_c))
    block_x = _divisor_block(hw, cap_x)
    return block_c, block_x


def _pl_channel_stats(x4):
    """Per-channel (sum, sum of squares) of an NCHW tensor, f32."""
    n, c, h, w = x4.shape
    hw = h * w
    x3 = x4.reshape(n, c, hw)
    block_c, block_x = _channel_blocks(n, c, hw)
    # channel blocks outermost so the (1, block_c) output tile stays
    # resident while the sequential grid walks batch and spatial blocks
    grid = (c // block_c, n, hw // block_x)
    in_spec = pl.BlockSpec((None, block_c, block_x),
                           lambda cb, nb, xb: (nb, cb, xb))
    out_spec = pl.BlockSpec((1, block_c), lambda cb, nb, xb: (0, cb))
    s, sq = pallas_call(
        _bn_stats_kernel,
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * 2,
        grid=grid, in_specs=[in_spec], out_specs=[out_spec, out_spec])(x3)
    return s.reshape(c), sq.reshape(c)


def _pl_apply_bn_relu(x4, scale, shift):
    n, c, h, w = x4.shape
    hw = h * w
    x3 = x4.reshape(n, c, hw)
    block_c, block_x = _channel_blocks(n, c, hw)
    grid = (n, c // block_c, hw // block_x)
    x_spec = pl.BlockSpec((None, block_c, block_x),
                          lambda nb, cb, xb: (nb, cb, xb))
    p_spec = pl.BlockSpec((1, block_c), lambda nb, cb, xb: (0, cb))
    out = pallas_call(
        _bn_apply_relu_kernel,
        out_shape=jax.ShapeDtypeStruct(x3.shape, x4.dtype),
        grid=grid, in_specs=[x_spec, p_spec, p_spec],
        out_specs=x_spec)(x3, scale.reshape(1, c), shift.reshape(1, c))
    return out.reshape(n, c, h, w)


_FUSED_CBR_ATTRS = None        # populated at registration below


def _cbr_conv(attrs, data, weight):
    from .nn import _convolution
    return _convolution(attrs, data, weight)


def _cbr_xla_impl(attrs, data, weight, gamma, beta, moving_mean,
                  moving_var, is_train):
    """The exact XLA composition: Convolution -> BatchNorm -> ReLU,
    sharing ops/nn.py's kernels so numerics are the composition's."""
    from .nn import _bn_fwd
    conv = _cbr_conv(attrs, data, weight)
    # _bn_fwd returns ([out, mean, var], [new_mean, new_var])
    outs, new_aux = _bn_fwd(attrs, [conv, gamma, beta],
                            [moving_mean, moving_var], is_train, None)
    y = jnp.maximum(outs[0], 0)
    return y, new_aux


def _cbr_scale_shift(attrs, gamma, mean, var, beta):
    eps = parse_float(attrs.get("eps", 1e-3))
    if parse_bool(attrs.get("fix_gamma", True)):
        gamma = jnp.ones_like(gamma)
    inv = jax.lax.rsqrt(var + eps)
    scale = (inv * gamma.astype(jnp.float32))
    shift = beta.astype(jnp.float32) - mean * scale
    return scale, shift


def _cbr_pallas_impl(attrs, data, weight, gamma, beta, moving_mean,
                     moving_var, is_train):
    conv = _cbr_conv(attrs, data, weight)
    use_global = parse_bool(attrs.get("use_global_stats", False))
    momentum = parse_float(attrs.get("momentum", 0.9))
    if is_train and not use_global:
        n, c, h, w = conv.shape
        cnt = float(n * h * w)
        s, sq = _pl_channel_stats(conv)
        mean = s / cnt
        var = jnp.maximum(sq / cnt - mean * mean, 0.0)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    scale, shift = _cbr_scale_shift(attrs, gamma, mean, var, beta)
    y = _pl_apply_bn_relu(conv, scale, shift)
    return y, [new_mean, new_var]


def _cbr_make(attrs, is_train):
    """custom_vjp closure over (static) attrs + train flag: Pallas
    forward emitting ``(y, new_mean, new_var)`` in one pass, backward
    recomputed through the XLA composition (aux cotangents discarded —
    moving statistics are side state, exactly as in BatchNorm)."""
    def xla_out(data, weight, gamma, beta, mm, mv):
        return _cbr_xla_impl(attrs, data, weight, gamma, beta,
                             jax.lax.stop_gradient(mm),
                             jax.lax.stop_gradient(mv), is_train)[0]

    @jax.custom_vjp
    def fn(data, weight, gamma, beta, mm, mv):
        y, new_aux = _cbr_pallas_impl(attrs, data, weight, gamma, beta,
                                      mm, mv, is_train)
        return y, new_aux[0], new_aux[1]

    def fwd(data, weight, gamma, beta, mm, mv):
        return fn(data, weight, gamma, beta, mm, mv), \
            (data, weight, gamma, beta, mm, mv)

    def bwd(res, cts):
        data, weight, gamma, beta, mm, mv = res
        ct_y = cts[0]                 # aux-state cotangents are zeros
        _, vjp_fn = jax.vjp(
            lambda d, w, g, b: xla_out(d, w, g, b, mm, mv),
            data, weight, gamma, beta)
        return vjp_fn(ct_y) + (jnp.zeros_like(mm), jnp.zeros_like(mv))

    fn.defvjp(fwd, bwd)
    return fn


def fused_conv_bn_relu(data, weight, gamma, beta, moving_mean,
                       moving_var, is_train=False, **attrs):
    """Functional surface of the fused conv+BN+ReLU Pallas kernel.

    Returns ``(out, [new_moving_mean, new_moving_var])`` — the same
    aux-state contract as BatchNorm (the executor swaps new aux after a
    training forward)."""
    y, nm, nv = _cbr_make(attrs, bool(is_train))(
        data, weight, gamma, beta, moving_mean, moving_var)
    return y, [nm, nv]


def _cbr_xla_variant(attrs, inputs, aux, is_train, rng):
    data, weight, gamma, beta = inputs
    y, new_aux = _cbr_xla_impl(attrs, data, weight, gamma, beta,
                               aux[0], aux[1], is_train)
    return [y], new_aux


def _cbr_pallas_variant(attrs, inputs, aux, is_train, rng):
    data, weight, gamma, beta = inputs
    y, nm, nv = _cbr_make(attrs, bool(is_train))(
        data, weight, gamma, beta, aux[0], aux[1])
    return [y], [nm, nv]


def _cbr_eligible(attrs, in_shapes, in_dtypes):
    kern = attrs.get("kernel")
    if kern is None or len(tuple(kern)) != 2:
        return False
    if len(in_shapes) < 1 or len(in_shapes[0]) != 4:
        return False
    return str(in_dtypes[0]) in ("float32", "bfloat16", "float16")


def _cbr_infer(attrs, in_shapes):
    from .nn import _conv_infer
    conv_attrs = dict(attrs, no_bias=True)
    new_in, out_s, _ = _conv_infer(conv_attrs, in_shapes[:2])
    nf = parse_int(attrs["num_filter"])
    c = (nf,)
    return [new_in[0], new_in[1], c, c], out_s, [c, c]


def _register_fused_conv_bn_relu():
    if "FusedConvBNReLU" in OP_REGISTRY:
        return
    from .nn import _CONV_ATTRS
    attrs = {k: v for k, v in _CONV_ATTRS.items() if k != "no_bias"}
    attrs.update({"eps": (parse_float, 1e-3),
                  "momentum": (parse_float, 0.9),
                  "fix_gamma": (parse_bool, True),
                  "use_global_stats": (parse_bool, False)})
    register("FusedConvBNReLU",
             inputs=("data", "weight", "gamma", "beta"),
             aux=("moving_mean", "moving_var"),
             full=_cbr_xla_variant,
             attr_spec=attrs, infer_shape=_cbr_infer,
             variants={"pallas": (_cbr_pallas_variant, _cbr_eligible)})


_register_fused_conv_bn_relu()


# ==========================================================================
# fused optimizer updates (promoted from rtc.py's correctness demo)
# ==========================================================================
_TILE_ROWS = 256
_LANES = 128


def _pad_to_tiles(v):
    n = v.size
    cols = _LANES
    rows = -(-n // cols)
    rows_pad = -(-rows // 16) * 16        # bf16-safe sublane multiple
    flat = jnp.ravel(v)
    flat = jnp.pad(flat, (0, rows_pad * cols - n))
    return flat.reshape(rows_pad, cols), n


def _tiled_elementwise(kernel, arrays, n_out):
    """Run an elementwise kernel over same-shaped operands: flatten,
    pad to (16k, 128) tiles, grid over row blocks, un-pad."""
    shape = arrays[0].shape
    padded = []
    n = None
    for a in arrays:
        p, n = _pad_to_tiles(a)
        padded.append(p)
    rows = padded[0].shape[0]
    # block rows: a 16-multiple divisor so the grid tiles rows exactly
    block = 16 * _divisor_block(rows // 16, _TILE_ROWS // 16)
    spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    outs = pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(padded[0].shape,
                                        padded[0].dtype)] * n_out,
        grid=(rows // block,),
        in_specs=[spec] * len(padded),
        out_specs=[spec] * n_out)(*padded)
    return tuple(o.reshape(-1)[:n].reshape(shape) for o in outs)


def _hyper(attrs):
    lr = parse_float(attrs["lr"])
    wd = parse_float(attrs.get("wd", 0.0))
    rescale = parse_float(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_gradient")
    clip = parse_float(clip) if clip is not None and \
        parse_float(clip) > 0 else None
    return lr, wd, rescale, clip


def _prep(g, w, wd, rescale, clip):
    g = g * rescale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    return g + wd * w


def _sgd_mom_kernel(attrs):
    lr, wd, rescale, clip = _hyper(attrs)
    momentum = parse_float(attrs.get("momentum", 0.0))

    def kernel(w_ref, g_ref, m_ref, ow_ref, om_ref):
        g = _prep(g_ref[...], w_ref[...], wd, rescale, clip)
        m = momentum * m_ref[...] - lr * g
        om_ref[...] = m
        ow_ref[...] = w_ref[...] + m
    return kernel


def _adam_kernel(attrs):
    lr, wd, rescale, clip = _hyper(attrs)
    b1 = parse_float(attrs.get("beta1", 0.9))
    b2 = parse_float(attrs.get("beta2", 0.999))
    eps = parse_float(attrs.get("epsilon", 1e-8))

    def kernel(w_ref, g_ref, mean_ref, var_ref, ow_ref, omean_ref,
               ovar_ref):
        w = w_ref[...]
        g = _prep(g_ref[...], w, wd, rescale, clip)
        mean = b1 * mean_ref[...] + (1 - b1) * g
        var = b2 * var_ref[...] + (1 - b2) * g * g
        omean_ref[...] = mean
        ovar_ref[...] = var
        ow_ref[...] = w - lr * mean / (jnp.sqrt(var) + eps)
    return kernel


def pallas_sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                          rescale_grad=1.0, clip_gradient=None):
    """Fused SGD-momentum update on jax arrays: (weight', mom')."""
    attrs = {"lr": lr, "momentum": momentum, "wd": wd,
             "rescale_grad": rescale_grad, "clip_gradient": clip_gradient}
    return _tiled_elementwise(_sgd_mom_kernel(attrs),
                              [weight, grad, mom], 2)


def pallas_adam_update(weight, grad, mean, var, lr, beta1=0.9,
                       beta2=0.999, epsilon=1e-8, wd=0.0,
                       rescale_grad=1.0, clip_gradient=None):
    """Fused Adam update on jax arrays: (weight', mean', var')."""
    attrs = {"lr": lr, "beta1": beta1, "beta2": beta2, "epsilon": epsilon,
             "wd": wd, "rescale_grad": rescale_grad,
             "clip_gradient": clip_gradient}
    return _tiled_elementwise(_adam_kernel(attrs),
                              [weight, grad, mean, var], 3)


def _opt_variant(op_name, kernel_builder, n_in, n_out):
    """Pallas variant of a registered optimizer op, with the uniform
    XLA-recompute custom VJP (updates are rarely differentiated, but
    the contract holds through either tier)."""
    xla_fwd = get_op(op_name).forward

    def variant(attrs, inputs, aux, is_train, rng):
        def pallas_fn(*vals):
            return _tiled_elementwise(kernel_builder(attrs), list(vals),
                                      n_out)

        def xla_fn(*vals):
            outs, _ = xla_fwd(attrs, list(vals), [], is_train, rng)
            return tuple(outs)

        fn = _xla_recompute_vjp(pallas_fn, xla_fn, n_in)
        return list(fn(*inputs)), []

    def eligible(attrs, in_shapes, in_dtypes):
        if len(set(tuple(s) for s in in_shapes)) != 1:
            return False
        return all(str(d) in ("float32", "bfloat16", "float16")
                   for d in in_dtypes)

    return variant, eligible


def _register_opt_variants():
    sgd = get_op("sgd_mom_update")
    if "pallas" not in sgd.variants:
        sgd.add_variant("pallas",
                        *_opt_variant("sgd_mom_update", _sgd_mom_kernel,
                                      3, 2))
    adam = get_op("adam_update")
    if "pallas" not in adam.variants:
        adam.add_variant("pallas",
                         *_opt_variant("adam_update", _adam_kernel, 4, 3))


def _register_softmax_ce_variant():
    sm = get_op("SoftmaxOutput")
    if "pallas" not in sm.variants:
        sm.add_variant("pallas", _softmax_ce_variant,
                       eligible=_softmax_ce_eligible)


_register_opt_variants()
_register_softmax_ce_variant()
