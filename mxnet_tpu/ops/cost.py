"""Cost metadata seeding: FLOPs/bytes per op for MFU accounting.

Every ``OpDef`` may carry ``flops(attrs, in_shapes)`` and
``bytes_moved(attrs, in_shapes)`` estimators for ONE forward execution
(telemetry/mfu.py turns them into per-op roofline positions and a
model-level MFU figure; the executor mirrors them into the
``executor.op_flops``/``executor.op_bytes`` counters at trace time).
This module attaches estimators to every op that matters for the
flagship workloads — the convolution/dense/batchnorm/softmax/optimizer
set that dominates ResNet-50 and LSTM step time — plus blanket
estimators for the elementwise/reduction/movement families so coverage
is the rule, not the exception. Ops left uncovered are surfaced by
analysis rule MF601 and ``tools/mxlint.py --mfu-audit`` instead of
silently under-counting.

Conventions (kept deliberately simple and auditable):

* one fused multiply-add = 2 FLOPs (XLA cost_analysis convention, so
  coverage ratios against ``compiled.cost_analysis()['flops']`` are
  apples-to-apples);
* bytes assume 4 B/element (master-param width); under bf16 compute the
  arithmetic-intensity *classification* is unchanged (both axes scale);
* data-movement ops (reshape/transpose/concat/slice/...) are 0 FLOPs
  but real bytes — they still occupy roofline positions.
"""
from __future__ import annotations

from ..base import parse_bool, parse_int, parse_tuple
from .registry import OP_REGISTRY

__all__ = ["seed_costs", "uncovered_ops", "partial_cost_ops",
           "optimizer_flops"]

_B = 4.0                                   # accounting bytes / element


def _prod(s):
    out = 1
    for d in s:
        out *= int(d)
    return out


def _elems(in_shapes, i=0):
    if i >= len(in_shapes) or in_shapes[i] is None:
        raise ValueError("unknown shape")
    return _prod(in_shapes[i])


def _sum_elems(in_shapes):
    return sum(_prod(s) for s in in_shapes if s is not None)


def _ntuple(v, n, default):
    t = parse_tuple(v) if v is not None else None
    if t is None:
        return (default,) * n
    if len(t) != n:
        t = tuple(t) + (default,) * (n - len(t))
    return t


# ---------------------------------------------------------------- shapes
def _conv_out_spatial(attrs, data_s):
    kernel = parse_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    dilate = _ntuple(attrs.get("dilate"), nd, 1)
    return tuple(
        (data_s[2 + i] + 2 * pad[i] - (dilate[i] * (kernel[i] - 1) + 1))
        // stride[i] + 1 for i in range(nd))


def _conv_flops(attrs, in_shapes):
    data_s = in_shapes[0]
    kernel = parse_tuple(attrs["kernel"])
    nf = parse_int(attrs["num_filter"])
    ng = parse_int(attrs.get("num_group", 1))
    out_sp = _conv_out_spatial(attrs, data_s)
    macs = _prod(out_sp) * data_s[0] * nf * (data_s[1] // ng) * \
        _prod(kernel)
    flops = 2.0 * macs
    if not parse_bool(attrs.get("no_bias", False)):
        flops += data_s[0] * nf * _prod(out_sp)
    return flops


def _conv_bytes(attrs, in_shapes):
    data_s = in_shapes[0]
    nf = parse_int(attrs["num_filter"])
    out = data_s[0] * nf * _prod(_conv_out_spatial(attrs, data_s))
    return _B * (_sum_elems(in_shapes) + out)


def _deconv_flops(attrs, in_shapes):
    # transposed conv: MACs = in_spatial * N * C_in * (nf/g) * kernel
    data_s = in_shapes[0]
    kernel = parse_tuple(attrs["kernel"])
    nf = parse_int(attrs["num_filter"])
    ng = parse_int(attrs.get("num_group", 1))
    return 2.0 * _prod(data_s) * (nf // ng) * _prod(kernel)


def _deconv_bytes(attrs, in_shapes):
    data_s = in_shapes[0]
    kernel = parse_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = _ntuple(attrs.get("stride"), nd, 1)
    pad = _ntuple(attrs.get("pad"), nd, 0)
    adj = _ntuple(attrs.get("adj"), nd, 0)
    nf = parse_int(attrs["num_filter"])
    sp = tuple(stride[i] * (data_s[2 + i] - 1) + kernel[i] - 2 * pad[i]
               + adj[i] for i in range(nd))
    return _B * (_sum_elems(in_shapes) + data_s[0] * nf * _prod(sp))


def _fc_flops(attrs, in_shapes):
    data_s = in_shapes[0]
    num_hidden = parse_int(attrs["num_hidden"])
    n = data_s[0]
    in_dim = _prod(data_s[1:])
    flops = 2.0 * n * in_dim * num_hidden
    if not parse_bool(attrs.get("no_bias", False)):
        flops += n * num_hidden
    return flops


def _fc_bytes(attrs, in_shapes):
    data_s = in_shapes[0]
    num_hidden = parse_int(attrs["num_hidden"])
    return _B * (_sum_elems(in_shapes) + data_s[0] * num_hidden)


def _cbr_flops(attrs, in_shapes):
    # conv + ~11 FLOPs/element of BN-normalize + ReLU epilogue
    data_s = in_shapes[0]
    nf = parse_int(attrs["num_filter"])
    out = data_s[0] * nf * _prod(_conv_out_spatial(attrs, data_s))
    return _conv_flops(dict(attrs, no_bias=True), in_shapes) + 11.0 * out


def _cbr_bytes(attrs, in_shapes):
    # the fusion's point: the epilogue adds no extra HBM round trip
    data_s = in_shapes[0]
    nf = parse_int(attrs["num_filter"])
    out = data_s[0] * nf * _prod(_conv_out_spatial(attrs, data_s))
    return _B * (_sum_elems(in_shapes) + out)


def _rnn_flops(attrs, in_shapes):
    # gates * 2 matmuls (i2h + h2h) * 2 FLOPs/MAC, per layer per step
    data_s = in_shapes[0]                   # (T, N, I)
    t, n, i = data_s[0], data_s[1], _prod(data_s[2:])
    h = parse_int(attrs["state_size"])
    layers = parse_int(attrs.get("num_layers", 1))
    gates = {"lstm": 4, "gru": 3}.get(
        str(attrs.get("mode", "lstm")).lower(), 1)
    d = 2 if parse_bool(attrs.get("bidirectional", False)) else 1
    per_layer = 2.0 * t * n * gates * h * (i + h)
    deeper = 2.0 * t * n * gates * h * (d * h + h) * max(0, layers - 1)
    return d * (per_layer + deeper)


def _rnn_bytes(attrs, in_shapes):
    data_s = in_shapes[0]
    h = parse_int(attrs["state_size"])
    d = 2 if parse_bool(attrs.get("bidirectional", False)) else 1
    out = data_s[0] * data_s[1] * d * h
    return _B * (_sum_elems(in_shapes) + out)


def _qfc_flops(attrs, in_shapes):
    # dense matmul + per-element weight dequant; bias add when present
    data_s = in_shapes[0]
    num_hidden = parse_int(attrs["num_hidden"])
    n = data_s[0]
    in_dim = _prod(data_s[1:])
    flops = 2.0 * n * in_dim * num_hidden + num_hidden * in_dim
    if not parse_bool(attrs.get("no_bias", False)):
        flops += n * num_hidden
    return flops


def _qfc_bytes(attrs, in_shapes):
    # quantized weights (int8 or fp8 storage) move at 1 B/element —
    # the tier's whole point; data, scales, bias and output stay at
    # the 4 B accounting width
    data_s, w_s = in_shapes[0], in_shapes[1]
    num_hidden = parse_int(attrs["num_hidden"])
    float_elems = _prod(data_s) + data_s[0] * num_hidden + \
        sum(_prod(s) for s in in_shapes[2:] if s is not None)
    return _B * float_elems + 1.0 * _prod(w_s)


def _qconv_flops(attrs, in_shapes):
    w_s = in_shapes[1]
    return _conv_flops(attrs, in_shapes) + float(_prod(w_s))


def _qconv_bytes(attrs, in_shapes):
    # 1 B/elem weights (int8 or fp8 storage), float everything else
    data_s, w_s = in_shapes[0], in_shapes[1]
    nf = parse_int(attrs["num_filter"])
    out = data_s[0] * nf * _prod(_conv_out_spatial(attrs, data_s))
    float_elems = _prod(data_s) + out + \
        sum(_prod(s) for s in in_shapes[2:] if s is not None)
    return _B * float_elems + 1.0 * _prod(w_s)


def _embedding_cost():
    # gather: ids + the N looked-up rows move; the untouched vocabulary
    # rows do not (one-pass gather, fused or not)
    def flops(attrs, in_shapes):
        return 0.0

    def nbytes(attrs, in_shapes):
        ids = _prod(in_shapes[0])
        d = in_shapes[1][1]
        return _B * (ids + 2.0 * ids * d)

    return flops, nbytes


def _attention_flops(attrs, in_shapes):
    b, h, t, d = in_shapes[0]
    return 4.0 * b * h * t * t * d


def _attention_bytes(attrs, in_shapes):
    return _B * 2.0 * _sum_elems(in_shapes)


def _attention_decode_flops(attrs, in_shapes):
    # S query tokens against the full C-capacity cache: qk^T + pv
    b, h, s, d = in_shapes[0]
    c = parse_int(attrs.get("capacity", 256))
    return 4.0 * b * h * s * c * d


def _attention_decode_bytes(attrs, in_shapes):
    # q/k/v/out move once at compute width; the K/V cache READ is
    # cursor-bounded — only the live prefix [0, cursor + S) streams
    # from HBM (the pallas variant's index-map clamp; a session's
    # cursor averages C/2) — and the write lands S rows per cache.
    # Both charge at the declared cache_dtype width: fp8 storage moves
    # 1 B/elem, the default compute-width cells 4 B
    b, h, s, d = in_shapes[0]
    c = parse_int(attrs.get("capacity", 256))
    itm = 1.0 if str(attrs.get("cache_dtype", "")).startswith(
        ("fp8", "float8", "e4m3", "e5m2")) else _B
    live = c / 2.0 + s
    return _B * 4.0 * b * h * s * d + \
        itm * 2.0 * b * h * (live + s) * d


def _rope_cost():
    # per element: 2 muls + 1 add on each half plus the trig tables
    def flops(attrs, in_shapes):
        return 8.0 * _elems(in_shapes)

    def nbytes(attrs, in_shapes):
        return _B * 2.0 * _elems(in_shapes)

    return flops, nbytes


def _dot_flops(attrs, in_shapes):
    a, b = in_shapes[0], in_shapes[1]
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    m = a[-1 if ta else 0] if len(a) > 1 else 1
    k = a[0 if ta else -1]
    n = b[-1 if not tb else 0] if len(b) > 1 else 1
    batch = _prod(a[:-2]) if len(a) > 2 else 1
    return 2.0 * batch * m * k * n


def _dot_bytes(attrs, in_shapes):
    return _B * 2.0 * _sum_elems(in_shapes)


# ------------------------------------------------------ family estimators
def _ew(flops_per_elem, reads=1, writes=1):
    """Elementwise family: k FLOPs/element of the largest operand."""
    def flops(attrs, in_shapes):
        return flops_per_elem * max(_prod(s) for s in in_shapes
                                    if s is not None)

    def nbytes(attrs, in_shapes):
        biggest = max(_prod(s) for s in in_shapes if s is not None)
        return _B * (_sum_elems(in_shapes) + writes * biggest)

    return flops, nbytes


def _move():
    """Pure data movement: 0 FLOPs, in+out bytes."""
    def flops(attrs, in_shapes):
        return 0.0

    def nbytes(attrs, in_shapes):
        return _B * 2.0 * _sum_elems(in_shapes)

    return flops, nbytes


def _reduce_cost():
    def flops(attrs, in_shapes):
        return float(_elems(in_shapes))

    def nbytes(attrs, in_shapes):
        return _B * _elems(in_shapes)

    return flops, nbytes


def _pool_cost():
    def flops(attrs, in_shapes):
        return float(_elems(in_shapes))

    def nbytes(attrs, in_shapes):
        return _B * 1.5 * _elems(in_shapes)   # out is ~stride^2 smaller

    return flops, nbytes


def _opt_cost(flops_per_elem, n_arrays):
    def flops(attrs, in_shapes):
        return flops_per_elem * _elems(in_shapes)

    def nbytes(attrs, in_shapes):
        return _B * n_arrays * _elems(in_shapes)

    return flops, nbytes


#: per-weight-element FLOPs of each optimizer update (mfu.optimizer_flops
#: reads this for fused-path updates that never appear as graph nodes)
OPTIMIZER_FLOPS_PER_ELEM = {
    "sgd": 4.0, "sgd_update": 4.0,
    "sgd_mom": 6.0, "sgd_mom_update": 6.0, "nag": 8.0, "ccsgd": 6.0,
    "adam": 12.0, "adam_update": 12.0,
    "rmsprop": 8.0, "rmsprop_update": 8.0,
    "rmspropalex_update": 12.0, "adagrad": 6.0, "adadelta": 10.0,
}


def optimizer_flops(name, n_params):
    """FLOPs of one full optimizer step over n_params weight elements."""
    per = OPTIMIZER_FLOPS_PER_ELEM.get(str(name).lower(), 6.0)
    return per * float(n_params)


# ----------------------------------------------------------------- tables
# dominant ops get dedicated estimators
_SPECIFIC = {
    "Convolution": (_conv_flops, _conv_bytes),
    "Deconvolution": (_deconv_flops, _deconv_bytes),
    "FullyConnected": (_fc_flops, _fc_bytes),
    "FusedConvBNReLU": (_cbr_flops, _cbr_bytes),
    "RNN": (_rnn_flops, _rnn_bytes),
    "dot": (_dot_flops, _dot_bytes),
    "batch_dot": (_dot_flops, _dot_bytes),
    "BatchNorm": _ew(10.0, writes=1),
    "LayerNorm": _ew(8.0),
    "FusedBiasGeLU": _ew(10.0),          # erf ≈ several VPU ops
    "QuantizedFullyConnected": (_qfc_flops, _qfc_bytes),
    "QuantizedConvolution": (_qconv_flops, _qconv_bytes),
    "attention": (_attention_flops, _attention_bytes),
    "attention_decode": (_attention_decode_flops, _attention_decode_bytes),
    "RoPE": _rope_cost(),
    "InstanceNorm": _ew(10.0),
    "L2Normalization": _ew(4.0),
    "LRN": _ew(8.0),
    "SoftmaxOutput": _ew(5.0),
    "SoftmaxActivation": _ew(5.0),
    "softmax_cross_entropy": _ew(5.0),
    "softmax": _ew(5.0),
    "log_softmax": _ew(5.0),
    "Pooling": _pool_cost(),
    "Dropout": _ew(2.0),
    "Activation": _ew(1.0),
    "LeakyReLU": _ew(2.0),
    "Embedding": _embedding_cost(),
    "sgd_update": _opt_cost(4.0, 3),
    "sgd_mom_update": _opt_cost(6.0, 5),
    "adam_update": _opt_cost(12.0, 7),
    "rmsprop_update": _opt_cost(8.0, 5),
    "rmspropalex_update": _opt_cost(12.0, 9),
    "pallas_sgd_mom_update": _opt_cost(6.0, 5),
    "pallas_flash_attention": (_attention_flops, _attention_bytes),
    "LinearRegressionOutput": _ew(2.0),
    "LogisticRegressionOutput": _ew(4.0),
    "MAERegressionOutput": _ew(2.0),
    "SVMOutput": _ew(4.0),
    "MakeLoss": _ew(1.0),
    "IdentityAttachKLSparseReg": _ew(6.0),
    "add_n": (lambda attrs, s: float(max(0, len(s) - 1)) * _elems(s),
              lambda attrs, s: _B * (_sum_elems(s) + _elems(s))),
}

_UNARY_1FLOP = {
    "abs", "ceil", "fix", "floor", "negative", "relu", "rint", "round",
    "sign", "square", "clip",
}
_UNARY_XCENDENTAL = {
    "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctanh", "cos",
    "cosh", "degrees", "exp", "expm1", "gamma", "gammaln", "log", "log10",
    "log1p", "log2", "radians", "rsqrt", "sigmoid", "sin", "sinh", "sqrt",
    "tan", "tanh", "smooth_l1",
}
_MOVEMENT = {
    "Reshape", "reshape", "Flatten", "flatten", "transpose", "Cast",
    "cast", "_copy", "identity", "BlockGrad", "stop_gradient",
    "make_loss", "Concat", "concat", "SliceChannel", "split", "slice",
    "slice_axis", "Crop", "expand_dims", "repeat", "tile", "reverse",
    "flip", "take", "pick", "one_hot", "SequenceLast", "SequenceMask",
    "SequenceReverse", "UpSampling", "Pad", "pad", "swapaxes",
    "SwapAxis", "broadcast_axis", "broadcast_to", "zeros_like",
    "ones_like", "_zeros", "_ones", "_arange", "where", "gather_nd",
    "batch_take", "stack",
}
_REDUCTIONS = {
    "sum", "mean", "prod", "nansum", "nanprod", "max", "min", "norm",
    "argmax", "argmin", "argmax_channel", "topk", "sort", "argsort",
}
_BINARY_NAMES = ("add", "sub", "mul", "div", "power", "hypot", "maximum",
                 "minimum", "equal", "not_equal", "greater",
                 "greater_equal", "lesser", "lesser_equal", "mod")


def _family_table():
    table = {}
    for name in _UNARY_1FLOP:
        table[name] = _ew(1.0)
    for name in _UNARY_XCENDENTAL:
        table[name] = _ew(4.0)          # transcendental ~ a few VPU ops
    for name in _MOVEMENT:
        table[name] = _move()
    for name in _REDUCTIONS:
        table[name] = _reduce_cost()
    for b in _BINARY_NAMES:
        k = 1.0
        for name in (f"elemwise_{b}" if b in ("add", "sub", "mul", "div")
                     else f"_{b}", f"broadcast_{b}", f"_{b}_scalar"):
            table[name] = _ew(k)
    for name in ("_rsub_scalar", "_rdiv_scalar", "_rpower_scalar",
                 "_rmod_scalar"):
        table[name] = _ew(1.0)
    return table


def seed_costs():
    """Attach estimators to every covered registry op (idempotent;
    specific estimators win over family blankets, and ops that already
    carry metadata — e.g. registered with flops=/bytes_moved= — keep
    their own)."""
    table = dict(_family_table())
    table.update(_SPECIFIC)
    for name, (flops, nbytes) in table.items():
        opdef = OP_REGISTRY.get(name)
        if opdef is not None and not opdef.has_cost():
            opdef.set_cost(flops=flops, bytes_moved=nbytes)


def uncovered_ops():
    """Registry ops still missing cost metadata (the --mfu-audit list).
    Aliases resolve to one OpDef, so each opdef reports once under its
    canonical name."""
    seen = {}
    for name, opdef in OP_REGISTRY.items():
        if not opdef.has_cost():
            seen.setdefault(id(opdef), opdef.name)
    return sorted(seen.values())


def partial_cost_ops():
    """Ops carrying exactly ONE of flops/bytes_moved — a half-seeded
    estimator under-counts one roofline axis while looking covered.
    Both the memory planner and the roofline fold per-op byte counts,
    so the consistency contract (tests/test_analysis.py) pins this
    list empty."""
    seen = {}
    for name, opdef in OP_REGISTRY.items():
        if (opdef.flops is None) != (opdef.bytes_moved is None):
            seen.setdefault(id(opdef), opdef.name)
    return sorted(seen.values())


seed_costs()
