"""Tensor-op library: the "numpy layer" of the framework.

Covers the reference's NNVM tensor op surface (reference:
src/operator/tensor/, ~10.9k LoC of mshadow kernels + cub sorts) as thin
declarative mappings onto jax.numpy/lax. There are no hand-written kernels
here on purpose: every op is an XLA HLO producer, so elementwise chains fuse
into matmul/conv epilogues and reductions tile onto the VPU — the work the
reference does with mshadow expression templates is done by the XLA compiler.

Inventory mirrors SURVEY.md Appendix A.2/A.3: unary math, binary (+scalar,
broadcast, logic) families, reductions, indexing (Embedding/take/one_hot/
pick), ordering (sort/topk/argsort), matrix ops (dot/batch_dot/transpose/
slice/...), init ops, control flow (where), and sampling ops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import (parse_tuple, parse_bool, parse_int, parse_float,
                    str_to_attr, merge_shape)
from .registry import register, alias

# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _axis_param(val):
    if val is None or val == "None" or val == "()":
        return None
    if isinstance(val, str):
        val = str_to_attr(val)
    if isinstance(val, (int, np.integer)):
        return int(val)
    return tuple(int(v) for v in val)


def _reduce(fn):
    def impl(attrs, x):
        axis = attrs.get("axis", None)
        keepdims = attrs.get("keepdims", False)
        exclude = attrs.get("exclude", False)
        if axis is not None and exclude:
            ax = (axis,) if isinstance(axis, int) else axis
            axis = tuple(i for i in range(x.ndim) if i not in
                         tuple(a % x.ndim for a in ax))
        return fn(x, axis=axis, keepdims=keepdims)
    return impl


_REDUCE_ATTRS = {"axis": (_axis_param, None), "keepdims": (parse_bool, False),
                 "exclude": (parse_bool, False)}


def _infer_elemwise(attrs, in_shapes, out_known=None):
    """Identity-shape inference: merge partials across inputs AND outputs
    (bidirectional fill — the mechanism that back-propagates batch dims
    into RNN begin_state vars)."""
    merged = None
    for s in list(in_shapes) + list(out_known or []):
        merged = merge_shape(merged, s)
    return [merged] * len(in_shapes), [merged], []


# --------------------------------------------------------------------------
# unary math family (reference: src/operator/tensor/elemwise_unary_op.cc,
# mshadow_op.h functor structs)
# --------------------------------------------------------------------------
_GAMMALN = lambda x: lax.lgamma(x.astype(jnp.float32)).astype(x.dtype)

_UNARY = {
    "abs": jnp.abs, "arccos": jnp.arccos, "arccosh": jnp.arccosh,
    "arcsin": jnp.arcsin, "arcsinh": jnp.arcsinh, "arctan": jnp.arctan,
    "arctanh": jnp.arctanh, "ceil": jnp.ceil, "cos": jnp.cos,
    "cosh": jnp.cosh, "degrees": jnp.degrees, "exp": jnp.exp,
    "expm1": jnp.expm1, "fix": jnp.fix, "floor": jnp.floor,
    "gamma": lambda x: jnp.exp(_GAMMALN(x)), "gammaln": _GAMMALN,
    "log": jnp.log, "log10": jnp.log10, "log1p": jnp.log1p,
    "log2": jnp.log2, "negative": jnp.negative, "radians": jnp.radians,
    "relu": lambda x: jnp.maximum(x, 0), "rint": jnp.rint,
    "round": jnp.round, "rsqrt": lambda x: 1.0 / jnp.sqrt(x),
    "sigmoid": jax.nn.sigmoid, "sign": jnp.sign, "sin": jnp.sin,
    "sinh": jnp.sinh, "sqrt": jnp.sqrt, "square": jnp.square,
    "tan": jnp.tan, "tanh": jnp.tanh,
}

for _name, _fn in _UNARY.items():
    register(_name, inputs=("data",),
             simple=(lambda attrs, x, _f=_fn: _f(x)),
             infer_shape=_infer_elemwise)

register("_copy", inputs=("data",), simple=lambda attrs, x: x,
         infer_shape=_infer_elemwise)
alias("identity", "_copy")


@register("BlockGrad", inputs=("data",), infer_shape=_infer_elemwise)
def _block_grad(attrs, x):
    return lax.stop_gradient(x)

alias("stop_gradient", "BlockGrad")


@register("make_loss", inputs=("data",), infer_shape=_infer_elemwise)
def _make_loss_t(attrs, x):
    return x


@register("smooth_l1", inputs=("data",),
          attr_spec={"scalar": (parse_float, 1.0)},
          infer_shape=_infer_elemwise)
def _smooth_l1(attrs, x):
    sigma2 = attrs.get("scalar", 1.0) ** 2
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / sigma2, 0.5 * sigma2 * x * x,
                     absx - 0.5 / sigma2)


@register("Cast", inputs=("data",), attr_spec={"dtype": (None, "float32")},
          infer_shape=_infer_elemwise)
def _cast(attrs, x):
    return x.astype(np.dtype(attrs.get("dtype", "float32")))

alias("cast", "Cast")


# --------------------------------------------------------------------------
# binary family: elemwise, broadcast, scalar (reference:
# elemwise_binary_{op,broadcast_op}*.cc)
# --------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "power": jnp.power,
    "hypot": jnp.hypot, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "equal": lambda a, b: (a == b).astype(a.dtype),
    "not_equal": lambda a, b: (a != b).astype(a.dtype),
    "greater": lambda a, b: (a > b).astype(a.dtype),
    "greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "lesser": lambda a, b: (a < b).astype(a.dtype),
    "lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "mod": jnp.mod,
}

for _name, _fn in _BINARY.items():
    register(f"elemwise_{_name}" if _name in ("add", "sub", "mul", "div")
             else f"_{_name}",
             inputs=("lhs", "rhs"),
             simple=(lambda attrs, a, b, _f=_fn: _f(a, b)),
             infer_shape=_infer_elemwise)
    register(f"broadcast_{_name}", inputs=("lhs", "rhs"),
             simple=(lambda attrs, a, b, _f=_fn: _f(a, b)))
    register(f"_{_name}_scalar", inputs=("data",),
             attr_spec={"scalar": (parse_float, 0.0)},
             simple=(lambda attrs, a, _f=_fn: _f(a, jnp.asarray(
                 attrs.get("scalar", 0.0), dtype=a.dtype))),
             infer_shape=_infer_elemwise)

for _name, _fn in (("rsub", lambda a, b: b - a), ("rdiv", lambda a, b: b / a),
                   ("rpower", lambda a, b: jnp.power(b, a)),
                   ("rmod", lambda a, b: jnp.mod(b, a))):
    register(f"_{_name}_scalar", inputs=("data",),
             attr_spec={"scalar": (parse_float, 0.0)},
             simple=(lambda attrs, a, _f=_fn: _f(a, jnp.asarray(
                 attrs.get("scalar", 0.0), dtype=a.dtype))),
             infer_shape=_infer_elemwise)

for _short, _canon in (("_plus", "elemwise_add"), ("_minus", "elemwise_sub"),
                       ("_mul", "elemwise_mul"), ("_div", "elemwise_div"),
                       ("_grad_add", "elemwise_add"),
                       ("_plus_scalar", "_add_scalar"),
                       ("_minus_scalar", "_sub_scalar"),
                       ("_rminus_scalar", "_rsub_scalar"),
                       ("_mul_scalar", "_mul_scalar2"),
                       ("_div_scalar", "_div_scalar2")):
    if _canon.endswith("2"):
        continue
    alias(_short, _canon)


@register("add_n", inputs=lambda attrs: [f"arg{i}" for i in range(
    int(attrs.get("num_args", 2)))],
    attr_spec={"num_args": (parse_int, 2)})
def _add_n(attrs, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out

alias("ElementWiseSum", "add_n")
alias("_sum", "add_n")


@register("broadcast_axis", inputs=("data",),
          attr_spec={"axis": (_axis_param, None), "size": (_axis_param, None)})
def _broadcast_axis(attrs, x):
    axes = attrs.get("axis")
    sizes = attrs.get("size")
    axes = (axes,) if isinstance(axes, int) else axes
    sizes = (sizes,) if isinstance(sizes, int) else sizes
    shape = list(x.shape)
    for ax, sz in zip(axes, sizes):
        shape[ax] = sz
    return jnp.broadcast_to(x, tuple(shape))


@register("broadcast_to", inputs=("data",),
          attr_spec={"shape": (parse_tuple, None)})
def _broadcast_to(attrs, x):
    tgt = list(attrs["shape"])
    for i, s in enumerate(tgt):
        if s == 0:
            tgt[i] = x.shape[i]
    return jnp.broadcast_to(x, tuple(tgt))


# --------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_{value,index}.cc)
# --------------------------------------------------------------------------
for _name, _fn in (("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
                   ("nansum", jnp.nansum), ("nanprod", jnp.nanprod),
                   ("max", jnp.max), ("min", jnp.min)):
    register(_name, inputs=("data",), attr_spec=dict(_REDUCE_ATTRS),
             simple=_reduce(_fn))

alias("sum_axis", "sum")
alias("max_axis", "max")
alias("min_axis", "min")


def _arg_reduce(fn):
    def impl(attrs, x):
        axis = attrs.get("axis", None)
        keepdims = attrs.get("keepdims", False)
        if axis is None:
            out = fn(jnp.ravel(x), axis=0)
            return out.astype(jnp.float32)
        out = fn(x, axis=axis)
        if keepdims:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.float32)
    return impl


register("argmax", inputs=("data",), attr_spec=dict(_REDUCE_ATTRS),
         simple=_arg_reduce(jnp.argmax))
register("argmin", inputs=("data",), attr_spec=dict(_REDUCE_ATTRS),
         simple=_arg_reduce(jnp.argmin))


@register("argmax_channel", inputs=("data",))
def _argmax_channel(attrs, x):
    return jnp.argmax(x, axis=-1).astype(jnp.float32)


@register("norm", inputs=("data",), attr_spec=dict(_REDUCE_ATTRS))
def _norm(attrs, x):
    axis = attrs.get("axis", None)
    keepdims = attrs.get("keepdims", False)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


@register("softmax_cross_entropy", inputs=("data", "label"))
def _softmax_xent(attrs, data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


# --------------------------------------------------------------------------
# init ops (reference: init_op.cc)
# --------------------------------------------------------------------------
def _init_shape_infer(attrs, in_shapes):
    return [], [tuple(attrs.get("shape", ()))], []


_INIT_ATTRS = {"shape": (parse_tuple, ()), "dtype": (None, "float32")}


@register("_zeros", inputs=(), attr_spec=dict(_INIT_ATTRS),
          infer_shape=_init_shape_infer)
def _zeros_op(attrs):
    return jnp.zeros(attrs.get("shape", ()), np.dtype(attrs.get("dtype", "float32")))


@register("_ones", inputs=(), attr_spec=dict(_INIT_ATTRS),
          infer_shape=_init_shape_infer)
def _ones_op(attrs):
    return jnp.ones(attrs.get("shape", ()), np.dtype(attrs.get("dtype", "float32")))


@register("_full", inputs=(), attr_spec={**_INIT_ATTRS,
                                         "value": (parse_float, 0.0)},
          infer_shape=_init_shape_infer)
def _full_op(attrs):
    return jnp.full(attrs.get("shape", ()), attrs.get("value", 0.0),
                    np.dtype(attrs.get("dtype", "float32")))


@register("_arange", inputs=(),
          attr_spec={"start": (parse_float, 0.0), "stop": (None, None),
                     "step": (parse_float, 1.0), "repeat": (parse_int, 1),
                     "dtype": (None, "float32")})
def _arange_op(attrs):
    stop = attrs.get("stop")
    stop = None if stop in (None, "None") else float(stop)
    arr = jnp.arange(attrs.get("start", 0.0), stop, attrs.get("step", 1.0),
                     np.dtype(attrs.get("dtype", "float32")))
    if attrs.get("repeat", 1) > 1:
        arr = jnp.repeat(arr, attrs["repeat"])
    return arr


@register("zeros_like", inputs=("data",), infer_shape=_infer_elemwise)
def _zeros_like(attrs, x):
    return jnp.zeros_like(x)


@register("ones_like", inputs=("data",), infer_shape=_infer_elemwise)
def _ones_like(attrs, x):
    return jnp.ones_like(x)


@register("_identity_with_attr_like_rhs", inputs=("lhs", "rhs"))
def _ident_like(attrs, lhs, rhs):
    return lhs


# --------------------------------------------------------------------------
# matrix ops (reference: matrix_op.cc)
# --------------------------------------------------------------------------
@register("dot", inputs=("lhs", "rhs"),
          attr_spec={"transpose_a": (parse_bool, False),
                     "transpose_b": (parse_bool, False)})
def _dot(attrs, a, b):
    if attrs.get("transpose_a"):
        a = a.T if a.ndim == 2 else jnp.moveaxis(a, -1, -2)
    if attrs.get("transpose_b"):
        b = b.T if b.ndim == 2 else jnp.moveaxis(b, -1, -2)
    # MXNet dot on >2d: collapses [a1..an-1, an] x [b1, b2..bm] over an==b1
    if a.ndim > 2 or b.ndim > 2:
        return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))
    return jnp.dot(a, b)


@register("batch_dot", inputs=("lhs", "rhs"),
          attr_spec={"transpose_a": (parse_bool, False),
                     "transpose_b": (parse_bool, False)})
def _batch_dot(attrs, a, b):
    if attrs.get("transpose_a"):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b"):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("transpose", inputs=("data",),
          attr_spec={"axes": (parse_tuple, None)})
def _transpose(attrs, x):
    axes = attrs.get("axes")
    if not axes:
        axes = None
    return jnp.transpose(x, axes)


@register("expand_dims", inputs=("data",), attr_spec={"axis": (parse_int, 0)})
def _expand_dims(attrs, x):
    return jnp.expand_dims(x, attrs["axis"])


@register("Reshape", inputs=("data",),
          attr_spec={"shape": (parse_tuple, None),
                     "target_shape": (parse_tuple, None),
                     "keep_highest": (parse_bool, False),
                     "reverse": (parse_bool, False)})
def _reshape(attrs, x):
    shape = attrs.get("shape") or attrs.get("target_shape")
    out = []
    src = list(x.shape)
    i = 0
    for s in shape:
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            continue  # handled by following -1/explicit pair; rare — fallthrough
        else:
            out.append(s); i += 1
    return jnp.reshape(x, tuple(out))

alias("reshape", "Reshape")


def _flatten_infer(attrs, in_shapes):
    # pure-python inference keeps Flatten off the jax.eval_shape
    # fallback — the static memory planner's trace-free guarantee
    # walks these shapes for every bundled model
    s = in_shapes[0]
    if s is None or any(d == 0 for d in s[1:]):
        return in_shapes, [None], []
    n = 1
    for d in s[1:]:
        n *= int(d)
    return in_shapes, [(s[0], n)], []


@register("Flatten", inputs=("data",), infer_shape=_flatten_infer)
def _flatten(attrs, x):
    return jnp.reshape(x, (x.shape[0], -1))

alias("flatten", "Flatten")


@register("slice", inputs=("data",),
          attr_spec={"begin": (parse_tuple, None), "end": (parse_tuple, None)})
def _slice(attrs, x):
    begin, end = attrs["begin"], attrs["end"]
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return x[idx]

alias("crop", "slice")


@register("slice_axis", inputs=("data",),
          attr_spec={"axis": (parse_int, 0), "begin": (parse_int, 0),
                     "end": (None, None)})
def _slice_axis(attrs, x):
    axis, begin = attrs["axis"], attrs["begin"]
    end = attrs.get("end")
    end = x.shape[axis] if end in (None, "None") else int(end)
    if end < 0:
        end += x.shape[axis]
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("_slice_assign", inputs=("lhs", "rhs"),
          attr_spec={"begin": (parse_tuple, None), "end": (parse_tuple, None)})
def _slice_assign(attrs, lhs, rhs):
    idx = tuple(slice(b, e) for b, e in zip(attrs["begin"], attrs["end"]))
    return lhs.at[idx].set(rhs)


@register("_crop_assign_scalar", inputs=("data",),
          attr_spec={"begin": (parse_tuple, None), "end": (parse_tuple, None),
                     "scalar": (parse_float, 0.0)})
def _crop_assign_scalar(attrs, x):
    idx = tuple(slice(b, e) for b, e in zip(attrs["begin"], attrs["end"]))
    return x.at[idx].set(attrs.get("scalar", 0.0))


@register("clip", inputs=("data",),
          attr_spec={"a_min": (parse_float, 0.0), "a_max": (parse_float, 0.0)},
          infer_shape=_infer_elemwise)
def _clip(attrs, x):
    return jnp.clip(x, attrs["a_min"], attrs["a_max"])


@register("repeat", inputs=("data",),
          attr_spec={"repeats": (parse_int, 1), "axis": (_axis_param, None)})
def _repeat(attrs, x):
    return jnp.repeat(x, attrs["repeats"], axis=attrs.get("axis"))


@register("tile", inputs=("data",), attr_spec={"reps": (parse_tuple, None)})
def _tile(attrs, x):
    return jnp.tile(x, attrs["reps"])


@register("reverse", inputs=("data",), shape_passthrough=True,
          attr_spec={"axis": (_axis_param, 0)})
def _reverse(attrs, x):
    ax = attrs.get("axis", 0)
    ax = (ax,) if isinstance(ax, int) else ax
    return jnp.flip(x, axis=ax)

alias("flip", "reverse")


@register("SwapAxis", inputs=("data",),
          attr_spec={"dim1": (parse_int, 0), "dim2": (parse_int, 0)})
def _swapaxis(attrs, x):
    return jnp.swapaxes(x, attrs["dim1"], attrs["dim2"])

alias("swapaxes", "SwapAxis")


@register("Pad", inputs=("data",),
          attr_spec={"mode": (None, "constant"),
                     "pad_width": (parse_tuple, None),
                     "constant_value": (parse_float, 0.0)})
def _pad(attrs, x):
    pw = attrs["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=attrs.get("constant_value", 0.0))
    if mode == "edge":
        return jnp.pad(x, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pairs, mode="reflect")
    raise ValueError(f"Pad mode {mode}")

alias("pad", "Pad")


# --------------------------------------------------------------------------
# indexing (reference: indexing_op.cc)
# --------------------------------------------------------------------------
def _embedding_infer(attrs, in_shapes):
    data_s, w_s = in_shapes
    in_dim = int(attrs["input_dim"])
    out_dim = int(attrs["output_dim"])
    w = (in_dim, out_dim)
    out = None
    if data_s is not None:
        out = tuple(data_s) + (out_dim,)
    return [data_s, w], [out], []


@register("Embedding", inputs=("data", "weight"),
          attr_spec={"input_dim": (parse_int, None),
                     "output_dim": (parse_int, None),
                     "dtype": (None, "float32"),
                     "scale": (parse_float, 1.0)},
          infer_shape=_embedding_infer)
def _embedding(attrs, data, weight):
    out = jnp.take(weight, data.astype(jnp.int32), axis=0)
    # optional post-lookup scale (transformer embedding-sharing wants
    # sqrt(d_model)); the 1.0 default is skipped so pre-scale graphs
    # stay bit-exact
    scale = parse_float(attrs.get("scale", 1.0))
    if scale != 1.0:
        out = out * jnp.asarray(scale, out.dtype)
    return out


@register("take", inputs=("a", "indices"),
          attr_spec={"axis": (parse_int, 0), "mode": (None, "clip")})
def _take(attrs, a, indices):
    mode = attrs.get("mode", "clip")
    return jnp.take(a, indices.astype(jnp.int32), axis=attrs.get("axis", 0),
                    mode="clip" if mode == "clip" else "wrap")


@register("batch_take", inputs=("a", "indices"))
def _batch_take(attrs, a, indices):
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("one_hot", inputs=("indices",),
          attr_spec={"depth": (parse_int, None), "on_value": (parse_float, 1.0),
                     "off_value": (parse_float, 0.0), "dtype": (None, "float32")})
def _one_hot(attrs, idx):
    depth = attrs["depth"]
    oh = jax.nn.one_hot(idx.astype(jnp.int32), depth,
                        dtype=np.dtype(attrs.get("dtype", "float32")))
    on, off = attrs.get("on_value", 1.0), attrs.get("off_value", 0.0)
    if on != 1.0 or off != 0.0:
        oh = oh * (on - off) + off
    return oh


@register("pick", inputs=("data", "index"),
          attr_spec={"axis": (parse_int, -1), "keepdims": (parse_bool, False)})
def _pick(attrs, data, index):
    axis = attrs.get("axis", -1)
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not attrs.get("keepdims", False):
        out = jnp.squeeze(out, axis=axis)
    return out


@register("where", inputs=("condition", "x", "y"))
def _where(attrs, cond, x, y):
    if cond.ndim == 1 and x.ndim > 1:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(cond != 0, x, y)


# --------------------------------------------------------------------------
# ordering (reference: ordering_op.cc over cub sorts)
# --------------------------------------------------------------------------
@register("sort", inputs=("data",),
          attr_spec={"axis": (_axis_param, -1), "is_ascend": (parse_bool, True)})
def _sort(attrs, x):
    axis = attrs.get("axis", -1)
    out = jnp.sort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", inputs=("data",),
          attr_spec={"axis": (_axis_param, -1), "is_ascend": (parse_bool, True)})
def _argsort(attrs, x):
    axis = attrs.get("axis", -1)
    out = jnp.argsort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)


def _topk_num_outputs(attrs):
    return 2 if attrs.get("ret_typ", "indices") == "both" else 1


@register("topk", inputs=("data",),
          attr_spec={"axis": (_axis_param, -1), "k": (parse_int, 1),
                     "ret_typ": (None, "indices"), "is_ascend": (parse_bool, False)},
          num_outputs=_topk_num_outputs)
def _topk(attrs, x):
    axis = attrs.get("axis", -1)
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    k = attrs.get("k", 1)
    ret = attrs.get("ret_typ", "indices")
    neg = attrs.get("is_ascend", False)
    xv = jnp.moveaxis(x, axis, -1)
    vals, idxs = lax.top_k(-xv if neg else xv, k)
    if neg:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(jnp.float32)
    if ret == "value":
        return vals
    if ret == "both":
        return vals, idxs
    if ret == "mask":
        mask = jnp.zeros_like(jnp.moveaxis(x, axis, -1))
        mask = mask.at[..., :].set(0)
        oh = jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1).astype(jnp.int32),
                            x.shape[axis], dtype=x.dtype).sum(-2)
        return jnp.moveaxis(oh, -1, axis)
    return idxs


# --------------------------------------------------------------------------
# sampling (reference: sample_op.cc) — functional JAX RNG under the hood
# --------------------------------------------------------------------------
def _sample_attr():
    return {"shape": (parse_tuple, ()), "dtype": (None, "float32")}


def _reg_sampler(name, draw):
    def fwd(attrs, inputs, aux, is_train, rng):
        shape = attrs.get("shape", ())
        dtype = np.dtype(attrs.get("dtype", "float32"))
        return [draw(attrs, rng, shape, dtype)], []
    register(name, inputs=(), full=fwd, need_rng=True,
             attr_spec={**_sample_attr(), **_SAMPLER_EXTRA.get(name, {})},
             infer_shape=_init_shape_infer)


_SAMPLER_EXTRA = {
    "_random_uniform": {"low": (parse_float, 0.0), "high": (parse_float, 1.0)},
    "_random_normal": {"loc": (parse_float, 0.0), "scale": (parse_float, 1.0)},
    "_random_gamma": {"alpha": (parse_float, 1.0), "beta": (parse_float, 1.0)},
    "_random_exponential": {"lam": (parse_float, 1.0)},
    "_random_poisson": {"lam": (parse_float, 1.0)},
    "_random_negative_binomial": {"k": (parse_int, 1), "p": (parse_float, 1.0)},
    "_random_generalized_negative_binomial": {
        "mu": (parse_float, 1.0), "alpha": (parse_float, 1.0)},
}

_reg_sampler("_random_uniform", lambda attrs, rng, shape, dtype:
             jax.random.uniform(rng, shape, dtype=dtype,
                                minval=attrs.get("low", 0.0),
                                maxval=attrs.get("high", 1.0)))
_reg_sampler("_random_normal", lambda attrs, rng, shape, dtype:
             attrs.get("loc", 0.0) + attrs.get("scale", 1.0) *
             jax.random.normal(rng, shape, dtype=dtype))
_reg_sampler("_random_gamma", lambda attrs, rng, shape, dtype:
             jax.random.gamma(rng, attrs.get("alpha", 1.0), shape,
                              dtype=dtype) * attrs.get("beta", 1.0))
_reg_sampler("_random_exponential", lambda attrs, rng, shape, dtype:
             jax.random.exponential(rng, shape, dtype=dtype) /
             attrs.get("lam", 1.0))
_reg_sampler("_random_poisson", lambda attrs, rng, shape, dtype:
             jax.random.poisson(rng, attrs.get("lam", 1.0), shape)
             .astype(dtype))
_reg_sampler("_random_negative_binomial", lambda attrs, rng, shape, dtype:
             _neg_binomial(rng, attrs.get("k", 1), attrs.get("p", 0.5),
                           shape).astype(dtype))
_reg_sampler("_random_generalized_negative_binomial",
             lambda attrs, rng, shape, dtype:
             _gen_neg_binomial(rng, attrs.get("mu", 1.0),
                               attrs.get("alpha", 1.0), shape).astype(dtype))

alias("uniform", "_random_uniform")
alias("random_uniform", "_random_uniform")
alias("normal", "_random_normal")
alias("random_normal", "_random_normal")
alias("random_gamma", "_random_gamma")
alias("random_exponential", "_random_exponential")
alias("random_poisson", "_random_poisson")
alias("random_negative_binomial", "_random_negative_binomial")
alias("random_generalized_negative_binomial",
      "_random_generalized_negative_binomial")


def _neg_binomial(rng, k, p, shape):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape)


def _gen_neg_binomial(rng, mu, alpha, shape):
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape)
