"""Fused RNN op.

The reference's RNN op is cuDNN-only (reference: src/operator/rnn-inl.h:103-120
CPU stubs "TODO", cudnn_rnn-inl.h is the real impl). TPU-native: the fused
multi-layer (bi)directional RNN is a ``lax.scan`` over time per layer —
XLA pipelines the gate matmuls onto the MXU and the scan keeps compile time
O(1) in sequence length (vs the unrolled cell library which specializes per
length).

Packed parameter layout matches rnn/rnn_cell.py FusedRNNCell._slice_weights
(itself following the reference's packed blob contract, rnn-inl.h:30-67):
for each layer then direction: all i2h gate weights, then all h2h gate
weights; after all weights, biases in the same order. Gate order: LSTM
i,f,c,o; GRU r,z,o (identical to the unfused cells, so pack/unpack
checkpoints interoperate).

Inputs: data (T, N, C), parameters (flat), state (L*D, N, H)
[, state_cell (L*D, N, H) for lstm]. Outputs: output (T, N, D*H)
[, state_out, state_cell_out when state_outputs=True].
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import parse_bool, parse_int, parse_float
from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _rnn_inputs(attrs):
    names = ["data", "parameters", "state"]
    if attrs.get("mode", "lstm") == "lstm":
        names.append("state_cell")
    return names


def _rnn_num_outputs(attrs):
    n = 1
    if parse_bool(attrs.get("state_outputs", False)):
        n += 1
        if attrs.get("mode", "lstm") == "lstm":
            n += 1
    return n


def _rnn_output_names(attrs):
    names = ["output"]
    if parse_bool(attrs.get("state_outputs", False)):
        names.append("state")
        if attrs.get("mode", "lstm") == "lstm":
            names.append("state_cell")
    return names


def _param_offsets(input_size, H, L, D, m):
    """Compute (layer, dir) -> weight/bias slice offsets in the flat blob.

    Mirrors FusedRNNCell._slice_weights traversal order exactly.
    """
    offsets = []
    p = 0
    for layer in range(L):
        for d in range(D):
            in_dim = input_size if layer == 0 else D * H
            wi_size = m * H * in_dim
            wh_size = m * H * H
            offsets.append({"wi": (p, m * H, in_dim)})
            p += wi_size
            offsets[-1]["wh"] = (p, m * H, H)
            p += wh_size
    for layer in range(L):
        for d in range(D):
            i = layer * D + d
            offsets[i]["bi"] = p
            p += m * H
            offsets[i]["bh"] = p
            p += m * H
    return offsets, p


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, gates):
            h, c = carry
            i = jax.nn.sigmoid(gates[:, 0 * H:1 * H])
            f = jax.nn.sigmoid(gates[:, 1 * H:2 * H])
            g = jnp.tanh(gates[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return (new_h, new_c)
        return step
    if mode == "gru":
        # gru needs the split i2h/h2h (reset gate multiplies h2h term);
        # handled in the scan body below, not here
        return None
    act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

    def step(carry, gates):
        (h,) = carry
        return (act(gates),)
    return step


def _run_layer(mode, x, wi, wh, bi, bh, h0, c0, H, reverse):
    """Scan one direction of one layer. x (T, N, in), returns (T, N, H)."""
    # hoist the input projection out of the scan: one big MXU matmul
    xw = jnp.einsum("tni,gi->tng", x, wi) + bi  # (T, N, m*H)
    if reverse:
        xw = jnp.flip(xw, axis=0)

    if mode == "gru":
        def body(carry, xg):
            (h,) = carry
            hg = jnp.dot(h, wh.T) + bh
            r = jax.nn.sigmoid(xg[:, 0 * H:1 * H] + hg[:, 0 * H:1 * H])
            z = jax.nn.sigmoid(xg[:, 1 * H:2 * H] + hg[:, 1 * H:2 * H])
            n = jnp.tanh(xg[:, 2 * H:3 * H] + r * hg[:, 2 * H:3 * H])
            # cuDNN/reference convention: h' = (1-z)*n + z*h
            new_h = n + z * (h - n)
            return ((new_h,), new_h)
        (hT,), out = lax.scan(body, (h0,), xw)
        cT = None
    elif mode == "lstm":
        step = _cell_step(mode, H)

        def body(carry, xg):
            h, c = carry
            gates = xg + jnp.dot(h, wh.T) + bh
            new_h, new_c = step((h, c), gates)
            return ((new_h, new_c), new_h)
        (hT, cT), out = lax.scan(body, (h0, c0), xw)
    else:
        step = _cell_step(mode, H)

        def body(carry, xg):
            (h,) = carry
            gates = xg + jnp.dot(h, wh.T) + bh
            (new_h,) = step((h,), gates)
            return ((new_h,), new_h)
        (hT,), out = lax.scan(body, (h0,), xw)
        cT = None
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, hT, cT


def _rnn_fwd(attrs, inputs, aux, is_train, rng):
    mode = attrs.get("mode", "lstm")
    H = parse_int(attrs["state_size"])
    L = parse_int(attrs["num_layers"])
    D = 2 if parse_bool(attrs.get("bidirectional", False)) else 1
    p_drop = parse_float(attrs.get("p", 0.0))
    m = _GATES[mode]

    data = inputs[0]
    params = inputs[1]
    state0 = inputs[2]
    cell0 = inputs[3] if mode == "lstm" else None
    T, N, input_size = data.shape

    offsets, total = _param_offsets(input_size, H, L, D, m)

    x = data
    h_finals = []
    c_finals = []
    for layer in range(L):
        outs = []
        for d in range(D):
            idx = layer * D + d
            off = offsets[idx]
            pwi, rows, cols = off["wi"]
            wi = lax.dynamic_slice(params, (pwi,),
                                   (rows * cols,)).reshape(rows, cols)
            pwh, rows_h, cols_h = off["wh"]
            wh = lax.dynamic_slice(params, (pwh,),
                                   (rows_h * cols_h,)).reshape(rows_h,
                                                               cols_h)
            bi = lax.dynamic_slice(params, (off["bi"],), (m * H,))
            bh = lax.dynamic_slice(params, (off["bh"],), (m * H,))
            h0 = state0[idx]
            c0 = cell0[idx] if cell0 is not None else None
            out, hT, cT = _run_layer(mode, x, wi, wh, bi, bh, h0, c0, H,
                                     reverse=(d == 1))
            outs.append(out)
            h_finals.append(hT)
            if cT is not None:
                c_finals.append(cT)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if is_train and p_drop > 0 and layer < L - 1 and rng is not None:
            keep = 1.0 - p_drop
            mask = jax.random.bernoulli(
                jax.random.fold_in(rng, layer), keep,
                x.shape).astype(x.dtype) / keep
            x = x * mask

    outputs = [x]
    if parse_bool(attrs.get("state_outputs", False)):
        outputs.append(jnp.stack(h_finals, axis=0))
        if mode == "lstm":
            outputs.append(jnp.stack(c_finals, axis=0))
    return outputs, []


def _rnn_infer(attrs, in_shapes):
    mode = attrs.get("mode", "lstm")
    H = parse_int(attrs["state_size"])
    L = parse_int(attrs["num_layers"])
    D = 2 if parse_bool(attrs.get("bidirectional", False)) else 1
    m = _GATES[mode]
    data_s = in_shapes[0]
    if data_s is None:
        n_out = _rnn_num_outputs(attrs)
        return in_shapes, [None] * n_out, []
    T, N, input_size = data_s
    _, total = _param_offsets(input_size, H, L, D, m)
    state_s = (L * D, N, H)
    new_in = [data_s, (total,), state_s]
    if mode == "lstm":
        new_in.append(state_s)
    outs = [(T, N, D * H)]
    if parse_bool(attrs.get("state_outputs", False)):
        outs.append(state_s)
        if mode == "lstm":
            outs.append(state_s)
    return new_in, outs, []


register("RNN", inputs=_rnn_inputs, full=_rnn_fwd, need_rng=True,
         num_outputs=_rnn_num_outputs, output_names=_rnn_output_names,
         num_visible=_rnn_num_outputs,
         attr_spec={"state_size": (parse_int, None),
                    "num_layers": (parse_int, None),
                    "mode": (None, "lstm"),
                    "bidirectional": (parse_bool, False),
                    "p": (parse_float, 0.0),
                    "state_outputs": (parse_bool, False),
                    "lstm_state_clip_min": (None, None),
                    "lstm_state_clip_max": (None, None)},
         infer_shape=_rnn_infer)
